"""Unified telemetry layer: registry semantics, exporters, the coordinator
/metrics route, and the meters the obs PR touched (EMAMeter debias,
thread-safe StopWatch)."""
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from distar_tpu.obs import (
    JsonlExporter,
    MetricsRegistry,
    render_prometheus,
    set_registry,
)


@pytest.fixture
def registry():
    """Fresh process-default registry per test (restored afterwards)."""
    reg = MetricsRegistry()
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


# ---------------------------------------------------------------- registry
def test_counter_monotonic(registry):
    c = registry.counter("distar_test_total", "help text")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    assert c.value == 3.5  # failed inc leaves the value untouched


def test_gauge_set_inc_dec(registry):
    g = registry.gauge("distar_test_gauge")
    g.set(10)
    g.inc(5)
    g.dec(2)
    assert g.value == 13


def test_histogram_quantiles_and_bounded_reservoir(registry):
    h = registry.histogram("distar_test_seconds", reservoir=100)
    for v in range(1, 101):
        h.observe(v)
    assert h.count == 100 and h.sum == 5050
    assert h.quantile(0.0) == 1
    assert h.quantile(0.5) == 51  # nearest-rank over [1..100]
    assert h.quantile(1.0) == 100
    # reservoir bounds memory: old samples fall out, count/sum are lifetime
    for v in range(1000, 1100):
        h.observe(v)
    assert h.count == 200
    assert h.quantile(0.0) == 1000  # the [1..100] window aged out


def test_same_name_labels_returns_same_instrument(registry):
    a = registry.counter("distar_x_total", token="t1")
    b = registry.counter("distar_x_total", token="t1")
    c = registry.counter("distar_x_total", token="t2")
    assert a is b and a is not c
    a.inc()
    assert b.value == 1 and c.value == 0


def test_type_conflict_and_bad_names_raise(registry):
    registry.counter("distar_dup")
    with pytest.raises(ValueError):
        registry.gauge("distar_dup")
    with pytest.raises(ValueError):
        registry.counter("0bad name")
    with pytest.raises(ValueError):
        registry.counter("distar_ok", **{"0badlabel": "v"})


def test_counter_thread_safety(registry):
    c = registry.counter("distar_mt_total")

    def spin():
        for _ in range(1000):
            c.inc()

    threads = [threading.Thread(target=spin) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000


# --------------------------------------------------------------- exporters
def test_prometheus_rendering_golden(registry):
    """Golden test for the text exposition format."""
    registry.counter("distar_env_steps_total", "env steps completed").inc(7)
    registry.gauge("distar_coordinator_queue_depth", "broker backlog", token="MP0traj").set(3)
    h = registry.histogram("distar_learner_step_seconds", "step time", reservoir=16)
    for v in (1.0, 2.0, 3.0, 4.0):
        h.observe(v)
    expected = "\n".join(
        [
            "# HELP distar_coordinator_queue_depth broker backlog",
            "# TYPE distar_coordinator_queue_depth gauge",
            'distar_coordinator_queue_depth{token="MP0traj"} 3',
            "# HELP distar_env_steps_total env steps completed",
            "# TYPE distar_env_steps_total counter",
            "distar_env_steps_total 7",
            "# HELP distar_learner_step_seconds step time",
            "# TYPE distar_learner_step_seconds summary",
            'distar_learner_step_seconds{quantile="0.5"} 3',
            'distar_learner_step_seconds{quantile="0.9"} 4',
            'distar_learner_step_seconds{quantile="0.99"} 4',
            "distar_learner_step_seconds_sum 10",
            "distar_learner_step_seconds_count 4",
            "",
        ]
    )
    assert render_prometheus(registry) == expected


def _parse_prometheus(text):
    """Minimal exposition-format parser: validates line shape, returns
    {series_name_with_labels: float}."""
    series = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            assert len(parts) >= 4 if line.startswith("# HELP") else len(parts) == 4
            continue
        assert not line.startswith("#"), f"unknown comment line {line!r}"
        name_part, _, value_part = line.rpartition(" ")
        assert name_part, f"malformed sample line {line!r}"
        series[name_part] = float(value_part)
    return series


def test_prometheus_label_escaping(registry):
    registry.gauge("distar_esc", label='va"l\\ue').set(1)
    text = render_prometheus(registry)
    assert 'label="va\\"l\\\\ue"' in text
    _parse_prometheus(text)


def test_jsonl_exporter_composes_with_scalar_sink(registry, tmp_path):
    registry.counter("distar_c_total").inc(2)
    h = registry.histogram("distar_h_seconds")
    h.observe(0.5)
    exporter = JsonlExporter(str(tmp_path), registry=registry)
    n = exporter.export(step=42)
    assert n >= 5  # counter + histogram count/sum/p50/p99
    lines = [
        json.loads(line)
        for line in open(os.path.join(str(tmp_path), "scalars.jsonl"))
    ]
    by_name = {rec["name"]: rec for rec in lines}
    assert by_name["distar_c_total"]["value"] == 2
    assert by_name["distar_h_seconds_count"]["value"] == 1
    assert all(rec["step"] == 42 for rec in lines)


# -------------------------------------------------- coordinator /metrics
def test_coordinator_stats_depth_agree(registry):
    """stats() applies the same age filter as depth() (they used to drift:
    stats counted raw lengths)."""
    from distar_tpu.comm import Coordinator

    co = Coordinator(max_age_s=0.2)
    co.register("traj", "1.2.3.4", 1111)
    assert co.stats() == {"traj": 1}
    assert co.depth("traj") == 1
    time.sleep(0.3)
    # the record aged past the serve window: BOTH views call it loss, not backlog
    assert co.depth("traj") == 0
    assert co.stats() == {"traj": 0}
    # raw lengths remain reachable explicitly
    assert co.stats(max_age_s=None) == {"traj": 1}
    assert co.depth("traj", max_age_s=None) == 1


def test_metrics_endpoint_serves_required_series(registry, tmp_path):
    """GET /metrics parses as Prometheus text and carries queue-depth,
    learner step-time and actor env-step-rate series produced by the real
    instrumented code paths."""
    from distar_tpu.actor.env_pool import EnvWorkerPool
    from distar_tpu.comm import Coordinator, CoordinatorServer
    from distar_tpu.envs import MockEnv
    from distar_tpu.learner.base_learner import BaseLearner
    from distar_tpu.obs import PROMETHEUS_CONTENT_TYPE

    # --- actor side: a real env pool stepping a mock env
    pool = EnvWorkerPool([lambda: MockEnv(episode_game_loops=10_000, seed=0)])
    pool.reset(0)
    stepped = 0
    deadline = time.time() + 30
    while stepped < 3 and time.time() < deadline:
        for e, kind, payload in pool.ready(timeout=5.0):
            if kind == "reset":
                obs = payload
                pool.submit(e, {})
            else:
                stepped += 1
                if stepped < 3:
                    pool.submit(e, {})
    pool.close()
    assert stepped >= 3

    # --- learner side: the real run loop on a trivial subclass
    class TinyLearner(BaseLearner):
        def _setup_state(self):
            self._state = {"params": {}}

        def _setup_dataloader(self):
            def gen():
                while True:
                    yield {}

            self._dataloader = gen()

        def _train(self, data):
            return {"total_loss": 0.0}

    learner = TinyLearner(
        {
            "common": {"experiment_name": "obs_test", "save_path": str(tmp_path)},
            "learner": {"save_freq": 10 ** 9, "log_freq": 10 ** 9},
        }
    )
    learner.run(max_iterations=2)

    # --- broker with backlog, serving the scrape
    co = Coordinator()
    co.register("MP0traj", "1.2.3.4", 1111)
    srv = CoordinatorServer(coordinator=co)
    srv.start()
    try:
        with urllib.request.urlopen(
            f"http://{srv.host}:{srv.port}/metrics", timeout=10
        ) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"] == PROMETHEUS_CONTENT_TYPE
            body = resp.read().decode()
        with urllib.request.urlopen(
            f"http://{srv.host}:{srv.port}/nope", timeout=10
        ) as resp:
            pass
    except urllib.error.HTTPError as e:
        assert e.code == 404  # non-/metrics GETs 404
    finally:
        srv.stop()
    series = _parse_prometheus(body)
    assert series['distar_coordinator_queue_depth{token="MP0traj"}'] == 1
    assert series["distar_learner_step_seconds_count"] == 2
    assert series["distar_env_steps_total"] >= 3
    assert series["distar_actor_env_step_rate"] > 0
    assert series["distar_learner_iterations_total"] == 2
    # step-phase breakdown rides along
    assert series['distar_learner_step_phase_seconds_count{phase="data_wait"}'] == 2
    assert series['distar_learner_step_phase_seconds_count{phase="device_step"}'] == 2
    assert series['distar_learner_step_phase_seconds_count{phase="host_callback"}'] == 2


# ------------------------------------------------------------ EMAMeter fix
def test_ema_meter_debiased_at_startup():
    """The docstring always promised debias; avg now delivers it: the first
    update reads back exactly, later reads are bias-corrected weighted means
    rather than zero-dragged raw EMAs."""
    from distar_tpu.utils.log import EMAMeter

    m = EMAMeter(alpha=0.99)
    assert m.avg == 0.0  # empty meter
    m.update(5.0)
    assert m.avg == pytest.approx(5.0)  # raw EMA would read 0.05 from zero-init
    assert m.val == 5.0
    m.update(7.0)
    # closed form: (alpha*5 + 7) / (alpha + 1) weighted mean
    assert m.avg == pytest.approx((0.99 * 5.0 + 7.0) / 1.99)
    assert m.count == 2


def test_ema_meter_converges_to_plateau():
    from distar_tpu.utils.log import EMAMeter

    m = EMAMeter(alpha=0.9)
    for _ in range(200):
        m.update(3.0)
    assert m.avg == pytest.approx(3.0)


# -------------------------------------------------------- StopWatch report
def test_stopwatch_thread_safe_and_reports(registry):
    from distar_tpu.utils.timing import StopWatch

    swatch = StopWatch(enabled=True)

    def spin(name):
        for _ in range(200):
            with swatch(name):
                pass

    threads = [threading.Thread(target=spin, args=(f"r{i % 2}",)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    s = swatch.summary()
    assert s["r0"]["num"] == 800 and s["r1"]["num"] == 800
    published = swatch.report(registry=registry)
    assert published["r0"]["num"] == 800
    assert swatch.times == {}  # reset: repeated reports never double-count
    assert registry.histogram("distar_stopwatch_seconds", region="r0").count == 800
    assert swatch.report(registry=registry) == {}


# ------------------------------------------------------------ no-print lint
def test_no_bare_prints_in_library_code():
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "lint_no_print", os.path.join(root, "tools", "lint_no_print.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    offences = mod.find_bare_prints(os.path.join(root, "distar_tpu"))
    assert offences == [], f"bare print() in library code: {offences}"


# ------------------------------------------------------- metric-name lint
def test_metric_names_follow_convention_and_are_documented():
    """Every metric registered in the tree matches distar_<subsystem>_<name>
    and appears in the docs/observability.md metric table (lint_metric_names
    mirrors lint_no_print: importable from tests, runnable standalone)."""
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "lint_metric_names", os.path.join(root, "tools", "lint_metric_names.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    problems = mod.lint(
        os.path.join(root, "distar_tpu"),
        os.path.join(root, "docs", "observability.md"),
    )
    assert problems == [], "\n".join(problems)


def test_prometheus_nonfinite_rendering(registry):
    """Non-finite values render per the text format (NaN/+Inf/-Inf) —
    repr() would emit 'nan'/'inf', which scrapers reject."""
    registry.gauge("distar_a").set(float("nan"))
    registry.gauge("distar_b").set(float("inf"))
    registry.gauge("distar_c").set(float("-inf"))
    text = render_prometheus(registry)
    assert "distar_a NaN" in text
    assert "distar_b +Inf" in text
    assert "distar_c -Inf" in text
    assert "nan" not in text and "inf" not in text  # no repr() leakage
