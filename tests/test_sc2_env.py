"""SC2Env orchestration tests over fake controllers (variable-delay
scheduling, win extraction, action dispatch) — the reference's
mock_sc2_env_comparison strategy one layer lower."""
import numpy as np
import pytest

from distar_tpu.envs.dummy_obs import build_dummy_game_info
from distar_tpu.envs.features import ProtoFeatures
from distar_tpu.envs.sc2_env import FakeController, SC2Env
from distar_tpu.lib import actions as ACT
from distar_tpu.lib import features as F


def _env(end_at=60, winner=1, **kwargs):
    gi = build_dummy_game_info()
    controllers = [
        FakeController(player_id=1, end_at=end_at, winner_player=winner),
        FakeController(player_id=2, end_at=end_at, winner_player=winner),
    ]
    feats = [ProtoFeatures(gi), ProtoFeatures(gi)]
    return SC2Env(controllers, feats, **kwargs), controllers


def _action(delay, action_type=0):
    return {
        "action_type": np.asarray(action_type),
        "delay": np.asarray(delay),
        "queued": np.asarray(0),
        "selected_units": np.zeros(F.MAX_SELECTED_UNITS_NUM, np.int64),
        "target_unit": np.asarray(0),
        "target_location": np.asarray(0),
    }


def test_reset_returns_feature_obs():
    env, _ = _env()
    obs = env.reset()
    assert set(obs) == {0, 1}
    assert obs[0]["entity_num"] == 8
    assert "value_feature" in obs[0]  # both_obs mode feeds the critic


def test_variable_delay_scheduling():
    """The env advances to the EARLIEST requested observation; only due
    agents get obs back."""
    env, controllers = _env(end_at=10_000)
    env.reset()
    obs, rewards, done, info = env.step({0: _action(delay=4), 1: _action(delay=10)})
    assert info["game_loop"] == 4
    assert 0 in obs and 1 not in obs  # agent 1 not due yet
    assert not done
    # next: agent 0 acts again; agent 1 still waiting until loop 10
    obs, rewards, done, info = env.step({0: _action(delay=6)})
    assert info["game_loop"] == 10
    assert set(obs) == {0, 1}


def test_action_dispatch_and_results():
    env, controllers = _env(end_at=10_000)
    env.reset()
    attack_pt = ACT.FUNC_ID_TO_ACTION_TYPE[2]
    a = _action(delay=2, action_type=attack_pt)
    a["selected_units"][0] = 0
    a["selected_units"][1] = 8  # end token (entity_num == 8)
    obs, *_ = env.step({0: a, 1: _action(delay=5)})
    assert len(controllers[0].acts_log) == 1
    cmd = controllers[0].acts_log[0][0]
    assert cmd["ability_id"] == ACT.ACTIONS[attack_pt]["general_ability_id"]
    assert cmd["unit_tags"] == [100]
    assert obs[0]["action_result"] == [1]


def test_win_extraction_and_done():
    env, _ = _env(end_at=6, winner=2)
    env.reset()
    obs, rewards, done, info = env.step({0: _action(delay=8), 1: _action(delay=8)})
    assert done
    assert rewards[1] == 1.0 and rewards[0] == -1.0
    assert info["outcome"] == [-1, 1]
    # stepping after done raises until reset
    with pytest.raises(AssertionError):
        env.step({0: _action(delay=1)})
    obs = env.reset()
    assert set(obs) == {0, 1}


def test_episode_length_cutoff():
    env, _ = _env(end_at=10_000, episode_length=12)
    env.reset()
    _, rewards, done, _ = env.step({0: _action(delay=16), 1: _action(delay=16)})
    assert done  # cut at episode_length, no winner
    assert rewards == {0: 0.0, 1: 0.0}


class _CountingController(FakeController):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.observe_calls = 0

    def observe(self, target_game_loop=0):
        self.observe_calls += 1
        return super().observe(target_game_loop=target_game_loop)


def test_human_mode_never_touches_human_controller():
    """A human's controller is never observed or acted; the agent side still
    plays and the human side's outcome comes from the agent's player_result
    (reference env.py:315-316, :384-385)."""
    gi = build_dummy_game_info()
    controllers = [
        _CountingController(player_id=1, end_at=20, winner_player=2),
        _CountingController(player_id=2, end_at=20, winner_player=2),
    ]
    feats = [ProtoFeatures(gi), ProtoFeatures(gi)]
    env = SC2Env(controllers, feats, human_indices=[1])
    obs = env.reset()
    assert set(obs) == {0}
    assert "value_feature" not in obs[0]  # both_obs forced off in human mode
    done = False
    while not done:
        obs, rewards, done, info = env.step({0: _action(delay=8)})
    assert controllers[1].observe_calls == 0
    assert controllers[1].acts_log == []
    assert rewards[0] == -1.0 and rewards[1] == 1.0  # human won
    assert 1 not in obs  # no terminal obs built for the human side


def test_save_replay_hook_fires_on_episode_end():
    saved = []
    env, _ = _env(end_at=6, save_replay_episodes=1,
                  replay_saver=lambda prefix: saved.append(prefix))
    env.reset()
    env.step({0: _action(delay=8), 1: _action(delay=8)})
    assert len(saved) == 1 and "outcome" in saved[0]


def test_lan_env_handshake_and_join():
    """LAN showmatch plumbing (role of reference lan_sc2_env/remote_sc2_env):
    host creates the game + serves the port config; the agent machine fetches
    it, joins via its own client, and drives a one-agent SC2Env. Both clients
    here talk to one fake server sharing a FakeGameCore (= the shared game)."""
    from distar_tpu.envs.sc2.fake_sc2 import FakeGameCore, FakeSC2Server
    from distar_tpu.envs.sc2.lan import LanPorts, LanSC2Env, host_lan_game
    from distar_tpu.envs.sc2.remote_controller import RemoteController

    server = FakeSC2Server(game=FakeGameCore(end_at=400, map_size=(120, 140)))
    try:
        host_controller = RemoteController("127.0.0.1", server.port, timeout_seconds=5)
        controller, handshake_port, _proc, join_thread = host_lan_game(
            "KairosJunction",
            race="zerg",
            realtime=False,
            controller=host_controller,
            ports=LanPorts(15000, 15001, 15002, 15003),
        )
        assert _proc is None  # injected controller: nothing launched

        env = LanSC2Env(
            "127.0.0.1",
            handshake_port,
            agent_race="zerg",
            controller_factory=lambda: RemoteController(
                "127.0.0.1", server.port, timeout_seconds=5
            ),
        )
        join_thread.join(timeout=10)
        assert not join_thread.is_alive(), "host join never completed"
        obs = env.reset()
        assert 0 in obs and "entity_info" in obs[0] and "spatial_info" in obs[0]
        for _ in range(4):
            out, reward, done, info = env.step({0: _action(delay=2)})
            if done:
                break
        env.close()
    finally:
        server.stop()
