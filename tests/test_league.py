"""League simulation tests — no game needed (league logic is game-agnostic).

Covers what the reference's test suite lacks entirely (SURVEY.md §4): pfsp
weighting properties, payoff warm-up priors, ELO convergence, matchmaking
branches, snapshot/reset lifecycle, resume roundtrip, and the HTTP API.
"""
import random

import numpy as np
import pytest

from distar_tpu.league import (
    ELORating,
    League,
    LeagueAPIServer,
    MainPlayer,
    Payoff,
    league_request,
    pfsp,
)


def _league(n_hist=2, one_phase_step=1000):
    cfg = {
        "league": {
            "active_players": {
                "player_id": ["MP0", "ME0", "EP0"],
                "checkpoint_path": ["mp0.ckpt", "me0.ckpt", "ep0.ckpt"],
                "pipeline": ["default"] * 3,
                "frac_id": [1] * 3,
                "z_path": ["3map.json"] * 3,
                "z_prob": [0.0] * 3,
                "teacher_id": ["T", "T", "T"],
                "teacher_path": ["teacher.ckpt"] * 3,
                "one_phase_step": [one_phase_step] * 3,
                "chosen_weight": [1.0] * 3,
            },
            "historical_players": {
                "player_id": [f"HP{i}" for i in range(n_hist)],
                "checkpoint_path": [f"hp{i}.ckpt" for i in range(n_hist)],
                "pipeline": ["default"] * n_hist,
                "frac_id": [1] * n_hist,
                "z_path": ["3map.json"] * n_hist,
                "z_prob": [0.0] * n_hist,
            },
        }
    }
    return League(cfg)


def test_pfsp_weightings():
    wr = np.array([0.1, 0.5, 0.9])
    sq = pfsp(wr, "squared")
    assert sq[0] > sq[1] > sq[2]  # favours opponents we lose to
    var = pfsp(wr, "variance")
    assert var[1] > var[0] and var[1] > var[2]  # favours even matches
    assert abs(pfsp(wr, "normal").sum() - 1) < 1e-9
    # all-zero winrates -> uniform
    np.testing.assert_allclose(pfsp(np.zeros(4)), np.full(4, 0.25))


def test_payoff_prior_and_update():
    p = Payoff(min_win_rate_games=10)
    assert p.win_rate_opponent("X") == 0.5  # prior below min games
    for _ in range(20):
        p.update("X", {"winrate": 1.0, "game_steps": 100, "game_iters": 5, "game_duration": 60})
    assert p.win_rate_opponent("X") == pytest.approx(1.0)
    assert p.game_count["X"] == 20


def test_elo_winner_gains():
    elo = ELORating()
    for _ in range(50):
        elo.update("A", "B", 1)
    r = elo.ratings(start_from_zero=False)
    assert r["A"] > r["B"]
    refit = elo.refit()
    assert refit["A"] > refit["B"]


def test_job_generation_branches():
    random.seed(0)
    lg = _league()
    branches = set()
    for _ in range(50):
        job = lg.actor_ask_for_job({"job_type": "train"})
        assert len(job["player_ids"]) == 2
        assert job["env_info"]["map_name"] == "KairosJunction"
        assert set(job) >= {
            "checkpoint_paths", "teacher_player_ids", "send_data_players",
            "update_players", "frac_ids", "z_path", "z_prob",
        }
        branches.add(job["branch"])
    assert branches & {"sp", "pfsp", "vs_main", "vs_main_eval"}


def test_vs_bot_job():
    lg = _league()
    lg.cfg.vs_bot = True
    job = lg.actor_ask_for_job({"job_type": "train"})
    assert job["branch"] == "train_bot"
    assert job["bot_id"].startswith("bot")
    assert len(job["env_info"]["player_ids"]) == 2


def test_snapshot_and_reset_lifecycle():
    lg = _league(one_phase_step=100)
    n_hist0 = len(lg.historical_players)
    # main player crosses one_phase_step -> snapshot, no reset (MainPlayer)
    reply = lg.learner_send_train_info("MP0", train_steps=150)
    assert len(lg.historical_players) == n_hist0 + 1
    assert "MP0H1" in lg.historical_players
    assert reply == {}
    # main exploiter always resets after snapshot -> reset path returned
    reply = lg.learner_send_train_info("ME0", train_steps=150)
    assert reply.get("reset_checkpoint_path") == "teacher.ckpt"
    assert any(pid.startswith("ME0H") for pid in lg.historical_players)


def test_result_ingestion_updates_payoff_and_elo():
    lg = _league()
    result = {
        "game_steps": 1000,
        "game_iters": 50,
        "game_duration": 600.0,
        "0": {"player_id": "MP0", "opponent_id": "HP0", "winloss": 1},
        "1": {"player_id": "HP0", "opponent_id": "MP0", "winloss": -1},
    }
    for _ in range(5):
        lg.actor_send_result(dict(result))
    mp0 = lg.active_players["MP0"]
    assert mp0.payoff.stat_info_record["HP0"]["winrate"].val == pytest.approx(1.0)
    assert mp0.total_game_count == 5
    assert lg.elo.ratings(start_from_zero=False)["MP0"] > lg.elo.ratings(start_from_zero=False)["HP0"]


def test_register_learner_and_resume(tmp_path):
    lg = _league()
    info = lg.register_learner("MP0", "127.0.0.1", 1234, 0, 1)
    assert info["checkpoint_path"] == "mp0.ckpt"
    lg.learner_send_train_info("MP0", train_steps=42)
    p = str(tmp_path / "league.resume")
    lg.save_resume(p)
    lg2 = _league()
    lg2.load_resume(p)
    assert lg2.active_players["MP0"].total_agent_step == 42


def test_http_api_roundtrip():
    lg = _league()
    server = LeagueAPIServer(lg)
    server.start()
    try:
        out = league_request(server.host, server.port, "actor_ask_for_job", {"job_type": "train"})
        assert out["code"] == 0 and len(out["info"]["player_ids"]) == 2
        out = league_request(server.host, server.port, "register_learner",
                             {"player_id": "MP0", "ip": "x", "port": 1, "rank": 0})
        assert out["info"]["checkpoint_path"] == "mp0.ckpt"
        out = league_request(server.host, server.port, "show_players", {})
        assert "MP0" in out["info"]["active"]
        out = league_request(server.host, server.port, "nonexistent", {})
        assert out["code"] == 404
    finally:
        server.stop()


def test_main_player_weak_opponent_fallback():
    """sp branch vs a weak main must fall back to that main's history."""
    random.seed(1)
    cfg_players = ["MP0", "MP1"]
    cfg = {
        "league": {
            "branch_probs": {"MainPlayer": {"sp": 1.0}},
            "active_players": {
                "player_id": cfg_players,
                "checkpoint_path": ["a.ckpt", "b.ckpt"],
                "pipeline": ["default"] * 2,
                "frac_id": [1] * 2,
                "z_path": ["3map.json"] * 2,
                "z_prob": [0.0] * 2,
                "teacher_id": ["T"] * 2,
                "teacher_path": ["t.ckpt"] * 2,
                "one_phase_step": [10 ** 9] * 2,
                "chosen_weight": [1.0] * 2,
            },
            "historical_players": {
                "player_id": ["HP0"],
                "checkpoint_path": ["hp0.ckpt"],
                "pipeline": ["default"],
                "frac_id": [1],
                "z_path": ["3map.json"],
                "z_prob": [0.0],
            },
            "payoff_min_win_rate_games": 1,
        }
    }
    lg = League(cfg)
    mp0 = lg.active_players["MP0"]
    # make MP0 terrible against MP1 -> sp branch must swap to history
    for _ in range(10):
        mp0.payoff.update("MP1", {"winrate": 0.0, "game_steps": 0, "game_iters": 0, "game_duration": 0})
    found_hist = False
    for _ in range(40):
        branch, home, away = mp0.get_branch_opponent(
            lg.historical_players, lg.active_players, lg.cfg.branch_probs, False
        )
        if away[0].player_id == "HP0":
            found_hist = True
    assert found_hist
