"""Arena skill-observatory tier-1 tests (PR 18).

Covers the full tentpole surface:

  * closed-form verification of the wired-up dormant ladder modules
    (ELO incremental update + draw-aware refit, Payoff 0.5-winrate prior
    and exponential-decay counters, Wilson confidence intervals);
  * the ArenaStore's deterministic uncertainty-directed scheduler (pure
    function of *reported* state), idempotent-key dedup, anchor floor,
    PFSP variance-weight preview, durability (journal save/load);
  * the chaos arena-drill's in-process twin: an evaluator abandoned
    mid-batch re-receives the identical assignment on restart — zero
    lost, zero double-counted by key construction;
  * the e2e acceptance: three toy checkpoint generations + two scripted
    anchors play a scheduled arena on jaxenv; ``attack_nearest`` ends
    rated above ``idle`` with confidence; the payoff matrix is
    non-trivial; ratings survive a coordinator restart via the durable
    store; ``GET /arena/ratings`` + ``/arena/payoff`` serve over a real
    CoordinatorServer; ``opsctl arena`` renders the scoreboard from
    shipped TSDB series.
"""
import json
import math
import os
import sys
import urllib.request
from argparse import Namespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distar_tpu.arena import (
    ANCHORS,
    ArenaEvaluator,
    ArenaStore,
    match_key,
    match_seed,
    set_arena_store,
    wilson_interval,
)
from distar_tpu.envs.jaxenv import EnvConfig, ScenarioConfig
from distar_tpu.league.elo import DRAW, ELORating, WIN
from distar_tpu.league.payoff import Payoff
from distar_tpu.obs import (
    FleetHealth,
    MetricsRegistry,
    default_rulebook,
    set_fleet_health,
    set_registry,
)

from conftest import SMALL_MODEL

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY_ENV = EnvConfig(units_per_squad=2)
# accounting-only scenario: outcome content doesn't matter, speed does
TINY_SCN = ScenarioConfig(units_per_squad=2, min_units=2, max_units=2,
                          episode_len=12)
# separating scenario: open terrain + long-enough timeout so attack_nearest
# actually converts engagements (mirrors test_jaxenv's pinned config)
FIGHT_SCN = ScenarioConfig(units_per_squad=2, min_units=2, max_units=2,
                           episode_len=96, spawn_margin=50.0,
                           spawn_spread=4.0, mirror_types=True,
                           blocked_frac=0.0)


@pytest.fixture
def registry():
    reg = MetricsRegistry()
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


@pytest.fixture
def arena_global():
    """Process-global arena-store slot, restored on teardown."""
    yield
    set_arena_store(None)


# ------------------------------------------------------------ ladder closed forms
def test_wilson_interval_closed_form():
    # no data -> the uninformative full interval
    assert wilson_interval(0, 0, 0) == (0.0, 1.0)
    # 8W/2L, z=1.96: hand-expanded Wilson score interval
    z, n, p = 1.96, 10.0, 0.8
    denom = 1 + z * z / n
    center = (p + z * z / (2 * n)) / denom
    half = z * math.sqrt(p * (1 - p) / n + z * z / (4 * n * n)) / denom
    lo, hi = wilson_interval(8, 0, 2)
    assert lo == pytest.approx(center - half)
    assert hi == pytest.approx(center + half)
    # draws count half a win: 4W/4D/2L has the same p-hat as 6W/4L
    assert wilson_interval(4, 4, 2) == wilson_interval(6, 0, 4)
    # interval is clamped into [0, 1]
    lo, hi = wilson_interval(3, 0, 0)
    assert 0.0 <= lo < 1.0 and hi == 1.0


def test_elo_first_game_closed_form():
    """K=44 incremental update from equal ratings: the winner takes exactly
    K * (1 - 0.5) = 22 points, symmetrically."""
    elo = ELORating()
    elo.update("a", "b", WIN)
    r = elo.ratings(start_from_zero=False)
    assert r["a"] == pytest.approx(1022.0)
    assert r["b"] == pytest.approx(978.0)


def test_elo_refit_counts_draws_as_half():
    """The payoff-consistency refit must read 50W/50D as a 0.75 score rate,
    not 0.5 — the divergence the wire-and-verify satellite existed to catch.
    The refit fixed point then satisfies expected(a,b) ~= 0.75, i.e. a gap
    of 400*log10(3) ~= 190.85 elo."""
    elo = ELORating()
    for _ in range(50):
        elo.update("a", "b", WIN)
    for _ in range(50):
        elo.update("a", "b", DRAW)
    refit = elo.refit()
    gap = refit["a"] - refit["b"]
    expected = 1.0 / (1.0 + 10 ** (-gap / 400.0))
    assert expected == pytest.approx(0.75, abs=1e-3)
    assert gap == pytest.approx(400.0 * math.log10(3.0), abs=1.0)


def test_payoff_prior_below_min_games():
    p = Payoff(min_win_rate_games=5)
    for _ in range(4):
        p.update("opp", {"winrate": 1.0})
    # 4 < 5 games: the 0.5 prior masks the perfect record
    assert p.win_rate_opponent("opp") == 0.5
    assert p.win_rate_opponent("opp", use_prior=False) == 1.0
    p.update("opp", {"winrate": 1.0})
    assert p.win_rate_opponent("opp") == 1.0


def test_payoff_decay_closed_form():
    """n results under decay d leave games = (1-d^n)/(1-d) — the geometric
    series the reference's recency weighting reduces to."""
    d, n = 0.9, 10
    p = Payoff(decay=d)
    assert p.decayed_win_rate("opp") == 0.5  # no games -> prior
    for _ in range(n):
        p.update("opp", {"winrate": 1.0})
    expected_games = (1 - d ** n) / (1 - d)
    assert p._decayed["opp"]["games"] == pytest.approx(expected_games)
    assert p.decayed_win_rate("opp") == pytest.approx(1.0)
    # one fresh loss outweighs a decayed win of the same age
    p.update("opp", {"winrate": 0.0})
    games = expected_games * d + 1.0
    wins = expected_games * d
    assert p.decayed_win_rate("opp") == pytest.approx(wins / games)
    assert p.decayed_win_rate("opp") < 1.0


# ----------------------------------------------------------------- match identity
def test_match_key_and_seed_determinism():
    assert match_key("a", "b", 3, 1) == "a|b|r3e1"
    # the seed is symmetric in the pair (home seat alternates by round) and
    # distinct across rounds, so every scenario set is fresh but replayable
    assert match_seed("a", "b", 0) == match_seed("b", "a", 0)
    assert match_seed("a", "b", 0) != match_seed("a", "b", 1)


# --------------------------------------------------------------------- scheduling
def test_scheduler_is_pure_in_reported_state(registry):
    store = ArenaStore()
    players = ["main:2", "main:1"]
    first = store.next_match(players, episodes=4)
    # re-asking without reporting returns the identical assignment — the
    # property that makes kill/restart exactly-once
    assert store.next_match(players, episodes=4) == first
    assert store.next_match(players, episodes=4) == first
    # cold start goes through the anchor floor: newest generation vs anchor
    assert {first["home"], first["away"]} == {"main:2", ANCHORS[0]}
    assert first["round"] == 0
    assert first["seed"] == match_seed(first["home"], first["away"], 0)


def test_scheduler_widest_ci_and_anchor_floor(registry):
    store = ArenaStore(anchor_period=4)
    players = ["main:1"]

    def play(assignment, winner="home", episodes=4):
        recs = [{"key": match_key(assignment["home"], assignment["away"],
                                  assignment["round"], i),
                 "home": assignment["home"], "away": assignment["away"],
                 "round": assignment["round"], "winner": winner,
                 "game_steps": 10, "duration_s": 0.1}
                for i in range(episodes)]
        return store.report_batch(recs)

    a0 = store.next_match(players)   # completed=0 -> anchor floor
    assert {a0["home"], a0["away"]} == {"main:1", "attack_nearest"}
    assert play(a0) == {"applied": 4, "duplicates": 0}
    # completed=1: widest-CI pick among unplayed pairs (width 1.0), ties
    # break lexicographically -> (attack_nearest, idle)
    a1 = store.next_match(players)
    assert {a1["home"], a1["away"]} == {"attack_nearest", "idle"}
    play(a1)
    a2 = store.next_match(players)   # next unplayed pair
    assert {a2["home"], a2["away"]} == {"idle", "main:1"}
    play(a2)
    # all pairs played 4 games each; a lopsided pair (p-hat at 0) has a
    # NARROWER Wilson interval than a balanced one, so the drawn pair wins
    store.report_batch([
        {"key": match_key("idle", "main:1", 9, i), "home": "idle",
         "away": "main:1", "round": 9, "winner": "draw",
         "game_steps": 10, "duration_s": 0.1} for i in range(4)])
    a3 = store.next_match(players)
    assert {a3["home"], a3["away"]} == {"idle", "main:1"}
    # round advanced past every applied round for the pair
    assert a3["round"] == 10


def test_report_batch_dedups_by_key(registry):
    store = ArenaStore()
    recs = [{"key": match_key("a", "b", 0, i), "home": "a", "away": "b",
             "round": 0, "winner": "home", "game_steps": 5,
             "duration_s": 0.1} for i in range(3)]
    assert store.report_batch(recs) == {"applied": 3, "duplicates": 0}
    # byte-identical replay (the crashed-after-ack evaluator): all deduped
    assert store.report_batch(recs) == {"applied": 0, "duplicates": 3}
    assert store.matches_total == 3
    assert store.duplicates_total == 3
    snap = store.ratings_snapshot()
    assert snap["players"]["a"]["games"] == 3
    # ELO moved for exactly 3 games, not 6
    assert store.elo.game_count == 3


def test_store_durability_roundtrip(registry, tmp_path):
    path = str(tmp_path / "arena.journal")
    store = ArenaStore(path=path)
    recs = [{"key": match_key("a", "b", 0, i), "home": "a", "away": "b",
             "round": 0, "winner": "home" if i else "draw", "game_steps": 7,
             "duration_s": 0.2} for i in range(4)]
    store.report_batch(recs)
    store.save()

    fresh = ArenaStore(path=path)
    assert fresh.maybe_load()
    assert fresh.ratings_snapshot() == store.ratings_snapshot()
    assert fresh.payoff_snapshot() == store.payoff_snapshot()
    # idempotency survives the restart: the seen-key set is journaled
    assert fresh.report_batch(recs) == {"applied": 0, "duplicates": 4}
    # and the scheduler resumes from the same round counters
    assert fresh.next_match(["a", "b"]) == store.next_match(["a", "b"])


def test_pfsp_preview_matches_hand_computed_variance_weights(registry):
    """GET /arena/payoff's read-only PFSP preview must equal the paper's
    variance weighting w*(1-w) over merged winrates, normalized, with 0.5
    for unplayed pairs."""
    store = ArenaStore(anchors=())  # no anchors: exact 3-player matrix
    for i in range(4):  # A beats B 3-1
        store.report_batch([{
            "key": match_key("A", "B", i, 0), "home": "A", "away": "B",
            "round": i, "winner": "home" if i else "away",
            "game_steps": 5, "duration_s": 0.1}])
    for i in range(2):  # A draws C twice
        store.report_batch([{
            "key": match_key("A", "C", i, 0), "home": "A", "away": "C",
            "round": i, "winner": "draw", "game_steps": 5,
            "duration_s": 0.1}])
    snap = store.payoff_snapshot()
    pv = snap["pfsp_preview"]
    # A's winrates: vs B = 0.75, vs C = 0.5 -> weights 0.1875, 0.25
    wb, wc = 0.75 * 0.25, 0.5 * 0.5
    assert pv["A"]["B"] == pytest.approx(wb / (wb + wc))
    assert pv["A"]["C"] == pytest.approx(wc / (wb + wc))
    # B: vs A = 0.25, vs C unplayed -> 0.5 prior
    wa, wc = 0.25 * 0.75, 0.5 * 0.5
    assert pv["B"]["A"] == pytest.approx(wa / (wa + wc))
    assert pv["B"]["C"] == pytest.approx(wc / (wa + wc))
    assert snap["pfsp_weighting"] == "variance"
    for row in pv.values():
        assert sum(row.values()) == pytest.approx(1.0)


def test_default_rulebook_carries_arena_rules():
    rules = {r.name: r for r in default_rulebook()}
    reg = rules["arena_rating_regression"]
    assert reg.metric == "distar_arena_main_rating_inverted"
    assert reg.op == "trending_up"
    stall = rules["arena_match_stall"]
    assert stall.metric == "distar_arena_matches_applied"
    assert stall.op == "stalled"


# ------------------------------------------------------- head_to_head match stats
def test_head_to_head_reports_per_match_stats(registry):
    from distar_tpu.envs.jaxenv.winrate import (attack_nearest_policy,
                                                idle_policy, head_to_head)

    res = head_to_head(attack_nearest_policy(), idle_policy(), episodes=4,
                       seed=3, env_cfg=TINY_ENV, scenario_cfg=TINY_SCN)
    assert len(res["matches"]) == 4
    counts = {"home": 0, "away": 0, "draw": 0}
    for m in res["matches"]:
        counts[m["winner"]] += 1
        assert m["draw"] == (m["winner"] == "draw")
        assert 0 < m["game_steps"] <= TINY_SCN.episode_len
    assert counts["home"] == res["wins"]
    assert counts["away"] == res["losses"]
    assert counts["draw"] == res["draws"]
    assert res["mean_game_steps"] == pytest.approx(
        np.mean([m["game_steps"] for m in res["matches"]]))
    assert res["duration_s"] > 0.0


# ------------------------------------------------------- chaos drill in-process twin
def test_evaluator_kill_restart_twin(registry, tmp_path):
    """In-process twin of ``tools/chaos.py arena-drill``: an evaluator that
    dies mid-batch (assignment taken + scenario run, nothing reported)
    loses nothing — the restarted evaluator re-receives the identical
    assignment, and a replayed ack dedups 100%."""
    store = ArenaStore(path=str(tmp_path / "journal"))
    ckpt = str(tmp_path / "ckpt")  # empty -> anchors-only roster
    os.makedirs(ckpt)

    def make_eval():
        return ArenaEvaluator(ckpt, model_cfg={}, store=store, episodes=3,
                              env_cfg=TINY_ENV, scenario_cfg=TINY_SCN)

    ev1 = make_eval()
    first = ev1.evaluate_once()
    assert first["ack"] == {"applied": 3, "duplicates": 0}

    # mid-batch death: take the assignment, never report (whole-batch
    # atomicity means the store is untouched)
    doomed = store.next_match(ev1.refresh_roster(), episodes=3)
    assert store.matches_total == 3

    ev2 = make_eval()  # the supervisor's restart
    second = ev2.evaluate_once()
    # the identical assignment is re-issued — the hole is filled exactly
    assert second["assignment"] == doomed
    assert second["ack"] == {"applied": 3, "duplicates": 0}
    assert store.matches_total == 6
    assert store.duplicates_total == 0

    # crashed-after-ack replay: same keys, fully deduped, totals unchanged
    home, away = doomed["home"], doomed["away"]
    replay = [{"key": match_key(home, away, doomed["round"], i),
               "home": home, "away": away, "round": doomed["round"],
               "winner": "draw", "game_steps": 1, "duration_s": 0.0}
              for i in range(3)]
    assert store.report_batch(replay) == {"applied": 0, "duplicates": 3}
    assert store.matches_total == 6


# ------------------------------------------------------------------ e2e acceptance
def _save_generations(ckpt_dir, params, steps):
    from distar_tpu.utils.checkpoint import CheckpointManager, save_checkpoint

    mgr = CheckpointManager(ckpt_dir)
    for g, step in enumerate(steps):
        gen = jax.tree.map(
            lambda x, g=g: x + 0.01 * g
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x, params)
        path = os.path.join(ckpt_dir, f"gen_{step}.ckpt")
        save_checkpoint(path, gen)
        mgr.record(path, step=step)


def _init_toy_params(model, env_cfg, scenario_cfg):
    from functools import partial

    from distar_tpu.envs.jaxenv.core import reset
    from distar_tpu.envs.jaxenv.obs import observe
    from distar_tpu.envs.jaxenv.scenario import ScenarioGenerator
    from distar_tpu.envs.jaxenv.winrate import model_policy

    gen = ScenarioGenerator(scenario_cfg)
    keys = jax.random.split(jax.random.PRNGKey(0), 2)
    states = jax.vmap(partial(reset, env_cfg))(jax.vmap(gen.generate)(keys))
    obs = jax.vmap(partial(observe, env_cfg), in_axes=(0, None))(states, 0)
    carry = model_policy(model, None).init_carry(2)
    return model.init(jax.random.PRNGKey(1), obs["spatial_info"],
                      obs["entity_info"], obs["scalar_info"],
                      obs["entity_num"], carry, jax.random.PRNGKey(2), None,
                      method=model.sample_action)


def test_arena_e2e_generations_vs_anchors(registry, tmp_path, capsys):
    """The PR's acceptance run: 3 toy checkpoint generations + 2 scripted
    anchors play a scheduled arena on jaxenv; attack_nearest out-rates idle
    with confidence; the matrix is non-trivial; ratings survive a
    coordinator restart; HTTP + opsctl consumption surfaces render."""
    from distar_tpu.comm.coordinator import CoordinatorServer
    from distar_tpu.model import Model, default_model_config
    from distar_tpu.utils import deep_merge_dicts

    fh = FleetHealth(rules=default_rulebook(), registry=registry)
    prev_fh = set_fleet_health(fh)
    journal = str(tmp_path / "arena.journal")
    ckpt_dir = str(tmp_path / "ckpt")
    os.makedirs(ckpt_dir)
    store = ArenaStore(path=journal)
    set_arena_store(store)
    srv = CoordinatorServer()
    srv.start()
    try:
        # phase A: anchors-only ladder — every batch is the scripted pair;
        # scripted episodes are cheap, so this phase banks the statistical
        # power (48 games) that grounds the confidence assertion below
        ev_a = ArenaEvaluator(ckpt_dir, model_cfg=SMALL_MODEL, store=store,
                              episodes=16, env_cfg=TINY_ENV,
                              scenario_cfg=FIGHT_SCN)
        for _ in range(3):
            out = ev_a.evaluate_once()
            assert {out["assignment"]["home"], out["assignment"]["away"]} \
                == set(ANCHORS)
            assert out["ack"]["duplicates"] == 0
        # phase B: three toy generations join mid-flight (roster refresh);
        # model batches are compile-dominated, so they run lean (4 episodes)
        model = Model(deep_merge_dicts(default_model_config(), SMALL_MODEL))
        params = _init_toy_params(model, TINY_ENV, FIGHT_SCN)
        _save_generations(ckpt_dir, params, steps=(100, 200, 300))
        ev_b = ArenaEvaluator(ckpt_dir, model_cfg=SMALL_MODEL, store=store,
                              episodes=4, env_cfg=TINY_ENV,
                              scenario_cfg=FIGHT_SCN)
        played = []
        for _ in range(4):
            out = ev_b.evaluate_once()
            played.append((out["assignment"]["home"],
                           out["assignment"]["away"]))
            assert out["ack"]["duplicates"] == 0
        # every generation met at least one anchor (rating scale grounded)
        met = {p for pair in played for p in pair}
        assert {"main:100", "main:200", "main:300"} <= met

        assert store.matches_total == 3 * 16 + 4 * 4
        assert store.duplicates_total == 0
        ratings = store.ratings_snapshot()
        atk, idl = (ratings["players"]["attack_nearest"],
                    ratings["players"]["idle"])
        assert atk["elo"] > idl["elo"]
        assert atk["trueskill_exposed"] > idl["trueskill_exposed"]
        # ... with confidence: the anchor pair's Wilson interval excludes 0.5
        payoff = store.payoff_snapshot()
        cell = next(c for c in payoff["cells"]
                    if {c["a"], c["b"]} == set(ANCHORS))
        assert cell["games"] == 48
        atk_low = (cell["wilson_low"] if cell["a"] == "attack_nearest"
                   else 1.0 - cell["wilson_high"])
        assert atk_low > 0.5
        # non-trivial matrix: several distinct pairs actually played
        assert sum(1 for c in payoff["cells"] if c["games"]) >= 4

        # durable restart: a fresh store reloads the journal bit-for-bit
        store.save()
        fresh = ArenaStore(path=journal)
        assert fresh.maybe_load()
        assert fresh.ratings_snapshot() == ratings
        assert fresh.payoff_snapshot() == payoff

        # HTTP consumption surfaces over the real coordinator
        def get(route):
            with urllib.request.urlopen(
                    f"http://{srv.host}:{srv.port}{route}", timeout=10) as r:
                return json.loads(r.read().decode())

        assert get("/arena/ratings") == ratings
        served = get("/arena/payoff")
        assert served == payoff
        # PFSP preview re-derived from the served matrix itself
        wrs, opps = [], []
        for c in served["cells"]:
            if "attack_nearest" in (c["a"], c["b"]):
                wr = (c["win_rate"] if c["a"] == "attack_nearest"
                      else 1.0 - c["win_rate"])
                opps.append(c["b"] if c["a"] == "attack_nearest" else c["a"])
                wrs.append(wr)
        raw = [w * (1.0 - w) for w in wrs]
        for opp, r in zip(opps, raw):
            assert served["pfsp_preview"]["attack_nearest"][opp] == \
                pytest.approx(r / sum(raw))

        # scoreboard from shipped TSDB series: sample the registry into the
        # fleet TSDB (what the coordinator's sampler thread does), then
        # render via the real opsctl CLI surface against the live server
        fh.sampler.sample_once()
        fh.sampler.sample_once()
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        try:
            import opsctl
        finally:
            sys.path.pop(0)
        rc = opsctl.cmd_arena(Namespace(addr=f"{srv.host}:{srv.port}",
                                        window=600.0, json=False))
        out = capsys.readouterr().out
        assert rc == 0
        assert "attack_nearest" in out and "idle" in out
        assert "rating trajectories (TSDB):" in out
        assert "pfsp preview" in out
        # the status digest line rides the same route
        opsctl._print_arena_digest(f"{srv.host}:{srv.port}")
        dig = capsys.readouterr().out
        assert "arena: 64 matches" in dig
    finally:
        srv.stop()
        set_arena_store(None)
        set_fleet_health(prev_fh)
        fh.stop()
