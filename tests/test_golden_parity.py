"""Golden parity vs the reference model's numerics.

tools/record_reference_golden.py runs the REFERENCE torch modules on inputs
from our feature schema and records inputs/outputs/state_dicts; here the
recorded weights are mapped into the Flax modules (model/ref_convert.py) and
the outputs must agree — the reference's exact behavior is the spec, and
this is the only guard against silent semantic drift (flipped axes,
off-by-one masks) in a ground-up reimplementation.

Fixtures are generated on demand (the reference + torch live in this image);
skipped cleanly where /root/reference is absent.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from distar_tpu.model import ref_convert  # noqa: E402
from distar_tpu.model.config import default_model_config  # noqa: E402

REF = "/root/reference"
GOLDEN_DIR = os.environ.get("GOLDEN_DIR", "/tmp/golden_ref")
RECORDER = os.path.join(os.path.dirname(__file__), "..", "tools", "record_reference_golden.py")

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference repo not available"
)


@pytest.fixture(scope="session")
def golden():
    if not os.path.exists(os.path.join(GOLDEN_DIR, "lnlstm.npz")):
        subprocess.run(
            [sys.executable, RECORDER, "--out", GOLDEN_DIR],
            check=True,
            timeout=1800,
            cwd="/tmp",
        )

    def load(name):
        z = np.load(os.path.join(GOLDEN_DIR, f"{name}.npz"))
        sd = {k[3:]: z[k] for k in z.files if k.startswith("sd/")}
        arrays = {k: z[k] for k in z.files if not k.startswith("sd/")}
        return sd, arrays

    return load


def test_lnlstm_parity(golden):
    from distar_tpu.ops.lstm import StackedLSTM

    sd, a = golden("lnlstm")
    T_, B, IN, HID, LAYERS = a["meta/dims"]
    lstm = StackedLSTM(hidden_size=int(HID), num_layers=int(LAYERS))
    params = ref_convert.convert_lnlstm(sd, int(LAYERS))
    ys, states = lstm.apply(params, jnp.asarray(a["in/xs"]))
    np.testing.assert_allclose(np.asarray(ys), a["out/ys"], atol=2e-5, rtol=1e-4)
    for i in range(int(LAYERS)):
        np.testing.assert_allclose(np.asarray(states[i][0]), a[f"out/h{i}"], atol=2e-5, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(states[i][1]), a[f"out/c{i}"], atol=2e-5, rtol=1e-4)


def test_entity_encoder_parity(golden):
    from distar_tpu.model.encoders import EntityEncoder

    sd, a = golden("entity_encoder")
    cfg = default_model_config()
    enc = EntityEncoder(cfg)
    params = ref_convert.convert_entity_encoder(sd, cfg)
    x = {
        k[3:]: jnp.asarray(v)
        for k, v in a.items()
        if k.startswith("in/") and k != "in/entity_num"
    }
    entity_embeddings, embedded_entity, mask = enc.apply(
        params, x, jnp.asarray(a["in/entity_num"])
    )
    n = int(a["in/entity_num"].max())
    np.testing.assert_allclose(
        np.asarray(entity_embeddings)[:, :n], a["out/entity_embeddings"][:, :n],
        atol=2e-4, rtol=1e-3,
    )
    np.testing.assert_allclose(
        np.asarray(embedded_entity), a["out/embedded_entity"], atol=2e-4, rtol=1e-3
    )


def test_scalar_encoder_parity(golden):
    from distar_tpu.model.encoders import ScalarEncoder

    sd, a = golden("scalar_encoder")
    cfg = default_model_config()
    enc = ScalarEncoder(cfg)
    params = ref_convert.convert_scalar_encoder(sd, cfg)
    x = {k[3:]: jnp.asarray(v) for k, v in a.items() if k.startswith("in/")}
    embedded_scalar, scalar_context, baseline_feature = enc.apply(params, x)
    np.testing.assert_allclose(
        np.asarray(embedded_scalar), a["out/embedded_scalar"], atol=2e-4, rtol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(scalar_context), a["out/scalar_context"], atol=2e-4, rtol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(baseline_feature), a["out/baseline_feature"], atol=2e-4, rtol=1e-3
    )


def test_spatial_encoder_parity(golden):
    from distar_tpu.model.encoders import SpatialEncoder

    sd, a = golden("spatial_encoder")
    cfg = default_model_config()
    enc = SpatialEncoder(cfg)
    params = ref_convert.convert_spatial_encoder(sd, cfg)
    x = {
        k[3:]: jnp.asarray(v)
        for k, v in a.items()
        if k.startswith("in/") and k != "in/scatter_map"
    }
    scatter_map = jnp.asarray(a["in/scatter_map"]).transpose(0, 2, 3, 1)  # NCHW->NHWC
    embedded_spatial, map_skip = enc.apply(params, x, scatter_map)
    np.testing.assert_allclose(
        np.asarray(embedded_spatial), a["out/embedded_spatial"], atol=2e-4, rtol=1e-3
    )
    for i, skip in enumerate(map_skip):
        ref = a[f"out/map_skip{i}"].transpose(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(skip), ref, atol=2e-4, rtol=1e-3)


def test_action_type_head_parity(golden):
    from distar_tpu.model.heads import ActionTypeHead

    sd, a = golden("action_type_head")
    cfg = default_model_config()
    head = ActionTypeHead(cfg)
    params = ref_convert.convert_action_type_head(sd, cfg)
    logits, _, embedding = head.apply(
        params, jnp.asarray(a["in/lstm_output"]), jnp.asarray(a["in/scalar_context"]),
        jnp.asarray(a["in/action_type"]),
    )
    np.testing.assert_allclose(np.asarray(logits), a["out/logits"], atol=2e-4, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(embedding), a["out/embedding"], atol=2e-4, rtol=1e-3)


@pytest.mark.parametrize("name,conv,label_key", [
    ("delay_head", "convert_delay_head", "delay"),
    ("queued_head", "convert_queued_head", "queued"),
])
def test_delay_queued_head_parity(golden, name, conv, label_key):
    from distar_tpu.model import heads

    sd, a = golden(name)
    cfg = default_model_config()
    head = {"delay_head": heads.DelayHead, "queued_head": heads.QueuedHead}[name](cfg)
    params = getattr(ref_convert, conv)(sd, cfg)
    logits, _, embedding = head.apply(
        params, jnp.asarray(a["in/embedding"]), jnp.asarray(a[f"in/{label_key}"])
    )
    np.testing.assert_allclose(np.asarray(logits), a["out/logits"], atol=2e-4, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(embedding), a["out/embedding"], atol=2e-4, rtol=1e-3)


def test_selected_units_head_parity(golden):
    """Teacher-forced pointer decode: per-step logits for the label steps and
    the final autoregressive embedding must match the reference loop."""
    from distar_tpu.model.heads import SelectedUnitsHead

    sd, a = golden("selected_units_head")
    cfg = default_model_config()
    head = SelectedUnitsHead(cfg)
    params = ref_convert.convert_selected_units_head(sd, cfg)
    logits, units, ae, num, extra = head.apply(
        params,
        jnp.asarray(a["in/embedding"]),
        jnp.asarray(a["in/entity_embedding"]),
        jnp.asarray(a["in/entity_num"]),
        selected_units=jnp.asarray(a["in/selected_units"]),
        selected_units_num=jnp.asarray(a["in/selected_units_num"]),
    )
    sun = a["in/selected_units_num"]
    seq_len = int(sun.max())
    ref_logits = a["out/logits"]  # [B, seq_len, N+1]
    ours = np.asarray(logits)[:, :seq_len]
    # compare per-lane label steps (the reference's post-end masking schedule
    # differs on loss-masked steps)
    for b in range(ref_logits.shape[0]):
        np.testing.assert_allclose(
            ours[b, : sun[b]], ref_logits[b, : sun[b]], atol=3e-4, rtol=1e-3
        )
    np.testing.assert_allclose(np.asarray(ae), a["out/embedding"], atol=3e-4, rtol=1e-3)


def test_target_unit_head_parity(golden):
    from distar_tpu.model.heads import TargetUnitHead

    sd, a = golden("target_unit_head")
    cfg = default_model_config()
    head = TargetUnitHead(cfg)
    params = ref_convert.convert_target_unit_head(sd, cfg)
    logits, _ = head.apply(
        params, jnp.asarray(a["in/embedding"]), jnp.asarray(a["in/entity_embedding"]),
        jnp.asarray(a["in/entity_num"]), jnp.asarray(np.zeros(2, np.int64)),
    )
    np.testing.assert_allclose(np.asarray(logits), a["out/logits"], atol=2e-4, rtol=1e-3)


def test_location_head_parity(golden):
    from distar_tpu.model.heads import LocationHead

    sd, a = golden("location_head")
    cfg = default_model_config()
    head = LocationHead(cfg)
    params = ref_convert.convert_location_head(sd, cfg)
    map_skip = [
        jnp.asarray(a[f"in/map_skip{i}"]).transpose(0, 2, 3, 1)
        for i in range(7)
    ]
    logits, _ = head.apply(
        params, jnp.asarray(a["in/embedding"]), map_skip,
        jnp.asarray(np.zeros(2, np.int64)),
    )
    np.testing.assert_allclose(np.asarray(logits), a["out/logits"], atol=5e-4, rtol=1e-3)


def test_value_baseline_parity(golden):
    from distar_tpu.model.value import ValueBaseline

    sd, a = golden("value_baseline")
    in_dim, res_dim, res_num, atan = a["meta/dims"]
    vb = ValueBaseline(res_dim=int(res_dim), res_num=int(res_num), atan=bool(atan))
    params = ref_convert.convert_value_baseline(sd, int(res_num))
    out = vb.apply(params, jnp.asarray(a["in/x"]))
    np.testing.assert_allclose(np.asarray(out), a["out/value"], atol=2e-4, rtol=1e-3)


def test_full_model_teacher_parity(golden):
    """The whole network end to end: reference compute_teacher_logit vs our
    teacher_logits after convert_model — encoder fusion, scatter connection,
    LSTM core, and the full autoregressive head chain in one shot."""
    from distar_tpu.model import Model

    sd, a = golden("full_model_teacher")
    cfg = default_model_config()
    model = Model(cfg)
    params = ref_convert.convert_model(sd, cfg)

    def group(prefix):
        return {
            k[len(prefix):]: jnp.asarray(v) for k, v in a.items() if k.startswith(prefix)
        }

    hidden = tuple(
        (jnp.zeros((2, 384)), jnp.zeros((2, 384))) for _ in range(3)
    )
    action_info = {k: jnp.asarray(v) for k, v in group("in/action/").items()}
    out = model.apply(
        params,
        group("in/spatial/"), group("in/entity/"), group("in/scalar/"),
        jnp.asarray(a["in/entity_num"]), hidden, action_info,
        jnp.asarray(a["in/selected_units_num"]),
        method=model.teacher_logits,
    )
    sun = a["in/selected_units_num"]
    for head, ref in {k[len("out/logit/"):]: v for k, v in a.items() if k.startswith("out/logit/")}.items():
        ours = np.asarray(out["logit"][head])
        if head == "selected_units":
            for b in range(ref.shape[0]):
                np.testing.assert_allclose(
                    ours[b, : sun[b]], ref[b, : sun[b]], atol=2e-3, rtol=1e-2,
                    err_msg=head,
                )
        else:
            np.testing.assert_allclose(ours, ref, atol=2e-3, rtol=1e-2, err_msg=head)
    for i in range(3):
        for j in range(2):
            np.testing.assert_allclose(
                np.asarray(out["hidden_state"][i][j]), a[f"out/hidden/{i}_{j}"],
                atol=1e-3, rtol=1e-2,
            )


def test_value_encoder_parity(golden):
    from distar_tpu.model.encoders import ValueEncoder

    sd, a = golden("value_encoder")
    cfg = default_model_config()
    enc = ValueEncoder(cfg)
    params = ref_convert.convert_value_encoder(sd, cfg)
    x = {k[3:]: jnp.asarray(v) for k, v in a.items() if k.startswith("in/")}
    out = enc.apply(params, x)
    np.testing.assert_allclose(np.asarray(out), a["out/embedding"], atol=3e-4, rtol=1e-3)
