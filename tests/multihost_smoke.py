"""Two-process jax.distributed smoke: the real multi-host init path.

Exercises parallel.dist.dist_init beyond single-process mesh shrinking
(VERDICT round-1 #10): two CPU processes x 4 virtual devices each form one
8-device global mesh; each process feeds its local shard of a dp-sharded
batch through a pjit train-ish step whose gradient psum rides the
cross-process collective layer.

Run directly (spawns both workers):   python tests/multihost_smoke.py
Run one worker (spawned internally):  python tests/multihost_smoke.py --rank N --port P
Wrapped by tests/test_multihost.py for CI.
"""
from __future__ import annotations

import os
import subprocess
import sys


def worker(rank: int, port: int) -> None:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from distar_tpu.parallel.dist import dist_init

    info = dist_init(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=rank,
        method="explicit",
    )
    assert info["world_size"] == 2, info

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distar_tpu.parallel import MeshSpec, make_mesh
    from distar_tpu.parallel.mesh import batch_sharding as lib_batch_sharding

    mesh = make_mesh(MeshSpec(dp=8))
    assert mesh.devices.size == 8

    batch_sharding = lib_batch_sharding(mesh)  # P("dp") on a dp-only mesh
    repl = NamedSharding(mesh, P())

    # one data-parallel "train step": per-shard loss grads psum over dp
    def step(w, x, y):
        def loss(w):
            pred = x @ w
            return jnp.mean((pred - y) ** 2)

        g = jax.grad(loss)(w)
        return w - 0.1 * g, loss(w)

    raw_step = step
    step = jax.jit(
        raw_step,
        in_shardings=(repl, batch_sharding, batch_sharding),
        out_shardings=(repl, repl),
    )

    rng = np.random.default_rng(0)  # same on both ranks
    w = jnp.asarray(rng.standard_normal((16, 4)).astype(np.float32))
    x_global = rng.standard_normal((32, 16)).astype(np.float32)
    y_global = (x_global @ np.asarray(w) * 0.5).astype(np.float32)

    # each process supplies ITS addressable shards of the global batch
    def make_global(arr, sharding):
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )

    x = make_global(x_global, batch_sharding)
    y = make_global(y_global, batch_sharding)
    losses = []
    for _ in range(3):
        w, l = step(w, x, y)
        losses.append(float(l))
    assert losses[-1] < losses[0], losses
    if rank == 0:
        print(f"multihost smoke ok: world={info['world_size']} losses={losses}")

    # ---- phase 2: ZeRO-style fsdp sharding ACROSS the process boundary.
    # Interleave the device order so every fsdp pair holds one device from
    # each process: the param all-gather and grad reduce-scatter must ride
    # the cross-process collective layer, not stay intra-host.
    devs = jax.devices()
    by_proc = {0: [d for d in devs if d.process_index == 0],
               1: [d for d in devs if d.process_index == 1]}
    assert len(by_proc[0]) == len(by_proc[1]) == 4
    order = [by_proc[p][i] for i in range(4) for p in (0, 1)]
    mesh2 = make_mesh(MeshSpec(dp=4, fsdp=2), order)
    pairs = mesh2.devices.reshape(4, 2)
    assert all(
        {d.process_index for d in row} == {0, 1} for row in pairs
    ), "fsdp pairs must straddle the two processes"

    w_sh = NamedSharding(mesh2, P("fsdp"))     # param sharded over fsdp
    bs2 = lib_batch_sharding(mesh2)            # the library's dp x fsdp spec
    repl2 = NamedSharding(mesh2, P())
    step2 = jax.jit(raw_step, in_shardings=(w_sh, bs2, bs2), out_shardings=(w_sh, repl2))

    w2 = make_global(np.asarray(rng.standard_normal((16, 4)), np.float32), w_sh)
    x2 = make_global(x_global, bs2)
    y2 = make_global(y_global, bs2)
    losses2 = []
    for _ in range(3):
        w2, l2 = step2(w2, x2, y2)
        losses2.append(float(l2))
    assert losses2[-1] < losses2[0], losses2
    assert "fsdp" in str(w2.sharding.spec)
    if rank == 0:
        print(f"multihost fsdp smoke ok: cross-process shards, losses={losses2}")


def main() -> int:
    # via the compat shim: the image doesn't ship portpicker (a bare import
    # here made collection/launch die on such images; the shim falls back to
    # a bind-port-0 stdlib pick)
    from distar_tpu.envs.sc2 import portpicker_compat as portpicker

    port = portpicker.pick_unused_port()
    env = dict(os.environ)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--rank", str(r), "--port", str(port)],
            env=env,
        )
        for r in range(2)
    ]
    rcs = [p.wait(timeout=600) for p in procs]
    if any(rcs):
        print(f"multihost smoke FAILED: rcs={rcs}")
        return 1
    return 0


if __name__ == "__main__":
    if "--rank" in sys.argv:
        rank = int(sys.argv[sys.argv.index("--rank") + 1])
        port = int(sys.argv[sys.argv.index("--port") + 1])
        worker(rank, port)
    else:
        sys.exit(main())
