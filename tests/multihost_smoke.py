"""Two-process jax.distributed smoke: the real multi-host init path.

Exercises parallel.dist.dist_init beyond single-process mesh shrinking
(VERDICT round-1 #10): two CPU processes x 4 virtual devices each form one
8-device global mesh; each process feeds its local shard of a dp-sharded
batch through a pjit train-ish step whose gradient psum rides the
cross-process collective layer.

Run directly (spawns both workers):   python tests/multihost_smoke.py
Run one worker (spawned internally):  python tests/multihost_smoke.py --rank N --port P
Wrapped by tests/test_multihost.py for CI.
"""
from __future__ import annotations

import os
import subprocess
import sys


def worker(rank: int, port: int) -> None:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from distar_tpu.parallel.dist import dist_init

    info = dist_init(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=rank,
        method="explicit",
    )
    assert info["world_size"] == 2, info

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distar_tpu.parallel import MeshSpec, make_mesh

    mesh = make_mesh(MeshSpec(dp=8))
    assert mesh.devices.size == 8

    batch_sharding = NamedSharding(mesh, P("dp"))
    repl = NamedSharding(mesh, P())

    # one data-parallel "train step": per-shard loss grads psum over dp
    def step(w, x, y):
        def loss(w):
            pred = x @ w
            return jnp.mean((pred - y) ** 2)

        g = jax.grad(loss)(w)
        return w - 0.1 * g, loss(w)

    step = jax.jit(
        step,
        in_shardings=(repl, batch_sharding, batch_sharding),
        out_shardings=(repl, repl),
    )

    rng = np.random.default_rng(0)  # same on both ranks
    w = jnp.asarray(rng.standard_normal((16, 4)).astype(np.float32))
    x_global = rng.standard_normal((32, 16)).astype(np.float32)
    y_global = (x_global @ np.asarray(w) * 0.5).astype(np.float32)

    # each process supplies ITS addressable shards of the global batch
    def make_global(arr):
        sharding = NamedSharding(mesh, P("dp"))
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )

    x = make_global(x_global)
    y = make_global(y_global)
    losses = []
    for _ in range(3):
        w, l = step(w, x, y)
        losses.append(float(l))
    assert losses[-1] < losses[0], losses
    if rank == 0:
        print(f"multihost smoke ok: world={info['world_size']} losses={losses}")


def main() -> int:
    import portpicker

    port = portpicker.pick_unused_port()
    env = dict(os.environ)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--rank", str(r), "--port", str(port)],
            env=env,
        )
        for r in range(2)
    ]
    rcs = [p.wait(timeout=600) for p in procs]
    if any(rcs):
        print(f"multihost smoke FAILED: rcs={rcs}")
        return 1
    return 0


if __name__ == "__main__":
    if "--rank" in sys.argv:
        rank = int(sys.argv[sys.argv.index("--rank") + 1])
        port = int(sys.argv[sys.argv.index("--port") + 1])
        worker(rank, port)
    else:
        sys.exit(main())
