"""MockEnv contract tests (the game-free test double every smoke loop and
CI pipeline rides on — previously covered only through those pipelines)."""
from distar_tpu.envs import MockEnv
from distar_tpu.lib import features as F


def _noop(delay):
    return {"action_type": 0, "delay": delay, "queued": 0,
            "selected_units": [], "target_unit": 0, "target_location": 0}


def test_obs_matches_feature_schema():
    env = MockEnv(seed=1)
    obs = env.reset()
    assert set(obs) == {0, 1}
    o = obs[0]
    for key in ("spatial_info", "scalar_info", "entity_info", "entity_num",
                "action_result", "battle_score", "opponent_battle_score"):
        assert key in o, key
    assert set(o["entity_info"]) == set(dict(F.ENTITY_INFO))
    for v in o["entity_info"].values():
        assert v.shape[0] == F.MAX_ENTITY_NUM
    assert 0 < int(o["entity_num"]) <= F.MAX_ENTITY_NUM


def test_step_advances_by_min_delay_and_terminates():
    env = MockEnv(episode_game_loops=100, seed=2)
    env.reset()
    obs, rewards, done, info = env.step({0: _noop(30), 1: _noop(10)})
    assert info["game_loop"] == 10  # earliest due agent drives the clock
    assert not done and all(r == 0.0 for r in rewards.values())
    # zero AND negative delays still make progress (no infinite loops)
    _, _, _, info = env.step({0: _noop(0), 1: _noop(0)})
    assert info["game_loop"] == 11
    _, _, _, info = env.step({0: _noop(-5), 1: _noop(3)})
    assert info["game_loop"] == 12

    while not done:
        obs, rewards, done, info = env.step({0: _noop(50), 1: _noop(50)})
    assert info["game_loop"] >= 100
    assert sorted(rewards.values()) == [-1.0, 1.0]  # zero-sum terminal
    assert info["winner"] in (0, 1)


def test_win_rule_first_and_reset_restarts_clock():
    env = MockEnv(episode_game_loops=20, win_rule="first", seed=3)
    env.reset()
    done = False
    while not done:
        _, rewards, done, info = env.step({0: _noop(8), 1: _noop(8)})
    assert info["winner"] == 0 and rewards[0] == 1.0

    obs = env.reset()
    assert float(obs[0]["scalar_info"]["time"]) == 0.0


def test_value_feature_toggle():
    env = MockEnv(include_value_feature=True, seed=4)
    obs = env.reset()
    assert "value_feature" in obs[0]
    assert "value_feature" not in MockEnv(seed=4).reset()[0]


def test_win_rule_battle_rewards_production():
    """The learnable rule: the agent whose actions built more army wins —
    an always-productive agent beats an always-idle one deterministically,
    battle_score tracks real production, and reset clears the tally."""
    from distar_tpu.lib import actions as ACT

    productive = ACT.CUMULATIVE_STAT_ACTIONS[1]  # a real build/train action
    env = MockEnv(episode_game_loops=60, win_rule="battle", seed=5)
    env.reset()
    done = False
    while not done:
        act0 = dict(_noop(10), action_type=productive)
        obs, rewards, done, info = env.step({0: act0, 1: _noop(10)})
    assert info["winner"] == 0 and rewards[0] == 1.0 and rewards[1] == -1.0
    assert info["scores"][0] > info["scores"][1] == 0.0
    assert obs[0]["battle_score"] == info["scores"][0]
    assert obs[1]["opponent_battle_score"] == info["scores"][0]

    env.reset()
    _, _, _, info = env.step({0: _noop(10), 1: _noop(10)})
    # action_type 0 (no_op) is not productive: fresh tally stays zero
    assert env._scores == [0.0, 0.0]


def test_rl_loss_config_overrides():
    """learner.loss yaml-surface overrides reach ReinforcementLossConfig
    (the reference's default_reinforcement_loss.yaml dial)."""
    from distar_tpu.learner.rl_learner import RL_LEARNER_DEFAULTS, make_loss_config
    from distar_tpu.utils import Config, deep_merge_dicts

    base = make_loss_config(RL_LEARNER_DEFAULTS.learner)
    assert base.kl_weight == 0.02 and base.use_dapo is False

    cfg = Config(deep_merge_dicts(
        dict(RL_LEARNER_DEFAULTS),
        {"learner": {"loss": {
            "kl_weight": 0.0, "entropy_weight": 3e-5,
            "pg_weights": [["winloss", 2.0]],
        }}},
    ))
    lc = make_loss_config(cfg.learner)
    assert lc.kl_weight == 0.0
    assert lc.entropy_weight == 3e-5
    assert lc.pg_weights == (("winloss", 2.0),)  # yaml lists -> tuples
    assert make_loss_config(
        Config(dict(RL_LEARNER_DEFAULTS.learner, loss={"use_dapo": True}))
    ).use_dapo is True  # loss.use_dapo must not collide with the top-level
