"""MockEnv contract tests (the game-free test double every smoke loop and
CI pipeline rides on — previously covered only through those pipelines)."""
from distar_tpu.envs import MockEnv
from distar_tpu.lib import features as F


def _noop(delay):
    return {"action_type": 0, "delay": delay, "queued": 0,
            "selected_units": [], "target_unit": 0, "target_location": 0}


def test_obs_matches_feature_schema():
    env = MockEnv(seed=1)
    obs = env.reset()
    assert set(obs) == {0, 1}
    o = obs[0]
    for key in ("spatial_info", "scalar_info", "entity_info", "entity_num",
                "action_result", "battle_score", "opponent_battle_score"):
        assert key in o, key
    assert set(o["entity_info"]) == set(dict(F.ENTITY_INFO))
    for v in o["entity_info"].values():
        assert v.shape[0] == F.MAX_ENTITY_NUM
    assert 0 < int(o["entity_num"]) <= F.MAX_ENTITY_NUM


def test_step_advances_by_min_delay_and_terminates():
    env = MockEnv(episode_game_loops=100, seed=2)
    env.reset()
    obs, rewards, done, info = env.step({0: _noop(30), 1: _noop(10)})
    assert info["game_loop"] == 10  # earliest due agent drives the clock
    assert not done and all(r == 0.0 for r in rewards.values())
    # zero AND negative delays still make progress (no infinite loops)
    _, _, _, info = env.step({0: _noop(0), 1: _noop(0)})
    assert info["game_loop"] == 11
    _, _, _, info = env.step({0: _noop(-5), 1: _noop(3)})
    assert info["game_loop"] == 12

    while not done:
        obs, rewards, done, info = env.step({0: _noop(50), 1: _noop(50)})
    assert info["game_loop"] >= 100
    assert sorted(rewards.values()) == [-1.0, 1.0]  # zero-sum terminal
    assert info["winner"] in (0, 1)


def test_win_rule_first_and_reset_restarts_clock():
    env = MockEnv(episode_game_loops=20, win_rule="first", seed=3)
    env.reset()
    done = False
    while not done:
        _, rewards, done, info = env.step({0: _noop(8), 1: _noop(8)})
    assert info["winner"] == 0 and rewards[0] == 1.0

    obs = env.reset()
    assert float(obs[0]["scalar_info"]["time"]) == 0.0


def test_value_feature_toggle():
    env = MockEnv(include_value_feature=True, seed=4)
    obs = env.reset()
    assert "value_feature" in obs[0]
    assert "value_feature" not in MockEnv(seed=4).reset()[0]
