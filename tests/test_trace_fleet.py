"""Fleet-wide distributed tracing: wire propagation, tail sampling,
exemplars, waterfall analysis, and the 2-gateway E2E acceptance.

Covers the PR 13 contracts:
  * wire ctx joins client/server spans under ONE trace_id over framed TCP
    (``transport="tcp"`` pinned per the PR 11 note) AND the shm leg;
  * ``traceparent`` round-trips over both HTTP frontends (serve + broker);
  * queue-wait vs service-time vs limiter-block attribution on live spans;
  * tail-sampler keep/drop invariants (error/shed traces never sampled out);
  * bounded-everything: TraceBuffer, TraceIngest, ExemplarStore all counted;
  * clock-skew clamps counted + carried raw;
  * Span outcome + error events, flight events carrying trace_id;
  * the E2E: loadgen against a 2-gateway fleet (real subprocesses), one
    gateway slowed -> opsctl trace retrieves the slow request's waterfall
    with client->gateway spans joined, and the latency-SLO alert fires with
    a resolvable exemplar trace_id.
"""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from distar_tpu.obs import (
    ExemplarStore,
    FlightRecorder,
    MetricsRegistry,
    Span,
    TraceBuffer,
    TraceIngest,
    annotate,
    build_waterfall,
    finish_trace,
    format_traceparent,
    get_flight_recorder,
    get_trace_buffer,
    join_trace,
    mark_hop,
    parse_traceparent,
    render_waterfall,
    set_exemplar_store,
    set_flight_recorder,
    set_registry,
    set_trace_buffer,
    set_tracing,
    start_trace,
    trace_record,
    wire_ctx,
)


@pytest.fixture(autouse=True)
def _tracing_on():
    """The suite-wide conftest default is DISTAR_TRACE=0 (unrelated tests
    must not pay the tracing hot path); every test in THIS module runs with
    minting on."""
    prev = set_tracing(True)
    yield
    set_tracing(prev)


@pytest.fixture
def registry():
    reg = MetricsRegistry()
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


@pytest.fixture
def buffer(registry):
    """Fresh keep-everything buffer (random_one_in=1) as process default."""
    buf = TraceBuffer(random_one_in=1)
    prev = set_trace_buffer(buf)
    yield buf
    set_trace_buffer(prev)


@pytest.fixture
def exemplars(registry):
    store = ExemplarStore()
    prev = set_exemplar_store(store)
    yield store
    set_exemplar_store(prev)


@pytest.fixture
def recorder():
    rec = FlightRecorder()
    prev = set_flight_recorder(rec)
    yield rec
    set_flight_recorder(prev)


def _count(registry, name, **labels):
    return registry.counter(name, **labels).value


# ------------------------------------------------------------ context core
def test_wire_ctx_joins_under_one_trace(registry, buffer):
    root = start_trace("client")
    w = wire_ctx(root)
    assert set(w) == {"trace_id", "span_id"}
    child = join_trace(w, "server", session="s1")
    assert child["trace_id"] == root["trace_id"]
    assert child["parent_span_id"] == root["span_id"]
    assert child["span_id"] != root["span_id"]
    # garbage/missing wire degrades to a fresh root, never raises
    fresh = join_trace({"trace_id": 7}, "server")
    assert "parent_span_id" not in fresh


def test_traceparent_roundtrip_and_garbage():
    ctx = start_trace("t")
    header = format_traceparent(ctx)
    parsed = parse_traceparent(header)
    assert parsed == wire_ctx(ctx)
    for garbage in (None, "", "00-xyz", "00-12-34-01", "nonsense-" * 10):
        assert parse_traceparent(garbage) is None


def test_mark_hop_clock_skew_counted_not_silent(registry, buffer):
    ctx = start_trace("skewy")
    # a cross-host hop stamped by a clock running AHEAD of ours
    ctx["hops"][-1]["ts"] = time.time() + 5.0
    dt = mark_hop(ctx, "cross_host", registry=registry)
    assert dt == 0.0  # clamped for the histogram...
    rec = ctx["hops"][-1]
    assert rec["raw_dt"] < -4.0  # ...but the raw delta rides the record
    assert _count(registry, "distar_trace_clock_skew_total", hop="cross_host") == 1
    finish_trace(ctx, registry=registry)
    kept = [r for r in buffer.records() if r["name"] == "skewy"]
    assert kept and kept[0]["skew"] is True
    # ...and the analyzer flags the waterfall instead of rendering lies
    report = build_waterfall(kept)
    assert report["skewed"] is True
    assert "CLOCK SKEW" in render_waterfall(report)


def test_span_exit_records_outcome_and_error_event(registry, recorder):
    with Span("fine", registry=registry) as sp:
        pass
    assert sp.outcome == "ok"
    ctx = start_trace("host")
    with pytest.raises(ValueError):
        with Span("doomed", registry=registry, trace=ctx) as sp:
            raise ValueError("boom")
    assert sp.outcome == "error"
    assert _count(registry, "distar_span_errors_total", span="doomed") == 1
    events = recorder.events(kind="span_error")
    assert len(events) == 1
    assert events[0]["error"] == "ValueError"  # the exception TYPE
    assert events[0]["name"] == "doomed"
    assert events[0]["trace_id"] == ctx["trace_id"]


def test_finish_trace_flight_event_carries_trace_id(registry, buffer, recorder):
    ctx = start_trace("trajectory")
    finish_trace(ctx, "learner_collate", registry=registry)
    events = recorder.events(kind="span")
    assert events and events[-1]["trace_id"] == ctx["trace_id"]
    # error outcomes are stamped on the event
    ctx2 = start_trace("trajectory")
    finish_trace(ctx2, "died", registry=registry, outcome="error")
    assert recorder.events(kind="span")[-1]["outcome"] == "error"


def test_finish_trace_idempotent(registry, buffer):
    ctx = start_trace("once")
    finish_trace(ctx, registry=registry)
    before = len(buffer.records())
    assert finish_trace(ctx, registry=registry) == 0.0
    assert len(buffer.records()) == before


# ------------------------------------------------------------ tail sampler
def test_tail_sampler_keep_drop_invariants(registry):
    buf = TraceBuffer(maxlen=64, random_one_in=10, registry=registry)

    def offer(dur, outcome="ok", name="req"):
        return buf.add({"trace_id": "t", "span_id": "s", "name": name,
                        "ts": time.time(), "dur_s": dur, "outcome": outcome,
                        "hops": []})

    # error/shed outcomes are NEVER sampled out
    for _ in range(50):
        assert offer(0.001, outcome="error")
        assert offer(0.001, outcome="shed")
    kept_outcome = _count(registry, "distar_tracebuf_kept_total", reason="outcome")
    assert kept_outcome == 100
    # a slow outlier against an established fast population is kept
    for _ in range(40):
        offer(0.001, name="other")
    assert offer(5.0, name="other")
    assert _count(registry, "distar_tracebuf_kept_total", reason="slow") >= 1
    # 1-in-N random keeps SOMETHING from a flat ok population...
    for _ in range(60):
        offer(0.0, name="flat")
    assert _count(registry, "distar_tracebuf_kept_total", reason="random") >= 1
    # ...and drops the rest, counted
    assert _count(registry, "distar_tracebuf_dropped_total",
                  reason="sampled_out") > 0
    # the ring is bounded: kept records never exceed maxlen, evictions counted
    assert len(buf.records()) <= 64
    assert _count(registry, "distar_tracebuf_dropped_total", reason="evicted") > 0


def test_trace_buffer_ship_cursor(registry):
    buf = TraceBuffer(random_one_in=1, registry=registry)
    for i in range(5):
        buf.add({"trace_id": f"t{i}", "span_id": "s", "name": "n",
                 "ts": 0.0, "dur_s": 0.1, "outcome": "ok", "hops": []})
    first = buf.unshipped()
    assert len(first) == 5
    assert buf.unshipped() == []  # cursor advanced; records still resident
    assert len(buf.records()) == 5


def test_trace_ingest_bounded_and_evicted(registry):
    ing = TraceIngest(max_per_source=4, max_sources=2, registry=registry)
    recs = [{"trace_id": f"t{i}", "span_id": f"s{i}", "name": "n",
             "ts": float(i), "dur_s": 0.01 * i, "outcome": "ok"}
            for i in range(6)]
    assert ing.ingest("a", recs) == 6
    assert ing.stats()["records"] == 4  # per-source ring evicted the oldest
    assert _count(registry, "distar_tracebuf_dropped_total", reason="evicted") == 2
    ing.ingest("b", recs[:2])
    # a third source past the cap is refused, counted
    assert ing.ingest("c", recs[:3]) == 0
    assert _count(registry, "distar_tracebuf_dropped_total",
                  reason="ingest_cap") == 3
    # member departure reclaims its traces (the TSDB series contract)
    assert ing.evict_source("a") == 4
    assert ing.stats()["sources"] == 1
    # queries filter and rank
    rows = ing.query(min_ms=10.0)
    assert all(r["dur_ms"] >= 10.0 for r in rows)
    spans = ing.get("t1")
    assert spans and spans[0]["source"] == "b"


def test_shipped_traces_evicted_with_member_departure(registry):
    """A departed member's traces leave the coordinator store through the
    SAME eviction path as its TSDB series (lease expiry / unregister)."""
    from distar_tpu.obs import TelemetryIngest, TimeSeriesStore

    traces = TraceIngest(registry=registry)
    ingest = TelemetryIngest(TimeSeriesStore(), registry=registry,
                             traces=traces)
    ingest.ingest({"source": "gw-1", "ts": time.time(),
                   "snapshot": {"distar_x": 1.0},
                   "endpoint": "127.0.0.1:9999",
                   "traces": [{"trace_id": "t1", "span_id": "s1",
                               "name": "serve_request", "ts": 0.0,
                               "dur_s": 0.1, "outcome": "ok"}]})
    assert traces.get("t1")
    assert ingest.evict_endpoint("127.0.0.1:9999") >= 1
    assert traces.get("t1") == []
    assert traces.stats()["sources"] == 0


def test_exemplar_store_bounded_lookup_merge(registry):
    ex = ExemplarStore(max_entries=2, registry=registry)
    assert ex.note("distar_x_seconds", "aaa", 1.0)
    assert ex.note("distar_y_seconds{span=t}", "bbb", 2.0)
    assert not ex.note("distar_z_seconds", "ccc", 3.0)  # capped, counted
    assert _count(registry, "distar_tracebuf_dropped_total",
                  reason="exemplar_cap") == 1
    # rule-metric reference finds its family exemplar by prefix
    hit = ex.lookup("distar_y_seconds{span=t}_p99")
    assert hit and hit["trace_id"] == "bbb"
    # merge: freshest wins per key
    ex.merge({"distar_x_seconds": {"trace_id": "zzz", "value": 9.0,
                                   "ts": time.time() + 10}})
    assert ex.lookup("distar_x_seconds")["trace_id"] == "zzz"


def test_alert_event_names_exemplar_trace(registry, exemplars, recorder):
    from distar_tpu.obs import FleetHealth, HealthRule

    fh = FleetHealth(rules=[HealthRule(
        name="lat_slo", metric="distar_serve_request_latency_seconds_p99",
        agg="last", op=">", threshold=0.01, window_s=60.0, for_count=1,
    )], registry=registry, recorder=recorder)
    exemplars.note("distar_serve_request_latency_seconds", "deadbeef01020304", 0.5)
    fh.store.record("distar_serve_request_latency_seconds_p99", 0.5,
                    source="gw")
    events = fh.evaluator.evaluate_once()
    firing = [e for e in events if e["state"] == "firing"]
    assert firing and firing[0]["exemplar_trace_id"] == "deadbeef01020304"
    # the flight recorder's alert event (what the crash bundle shows)
    # carries it too
    alerts = recorder.events(kind="alert")
    assert alerts and alerts[-1]["exemplar_trace_id"] == "deadbeef01020304"


# --------------------------------------------------------- wire propagation
def test_serve_tcp_wire_propagation_and_attribution(registry, buffer, exemplars):
    from distar_tpu.serve import (
        InferenceGateway,
        MockModelEngine,
        ServeClient,
        ServeTCPServer,
    )

    eng = MockModelEngine(4, params={"version": "v1"})
    gw = InferenceGateway(eng).start()
    gw.load_version("v1", params={"version": "v1"}, activate=True)
    # transport PINNED to tcp (the PR 11 note: colocated clients negotiate
    # shm by default and would silently dodge the TCP wire)
    srv = ServeTCPServer(gw, transport="tcp").start()
    try:
        with ServeClient(srv.host, srv.port, transport="tcp") as c:
            out = c.act("s1", {"x": np.zeros((2, 2), np.float32)})
        assert "trace_id" in out
        recs = buffer.records()
        client = [r for r in recs if r["name"] == "serve_client"]
        server = [r for r in recs if r["name"] == "serve_request"]
        assert client and server
        assert client[0]["trace_id"] == server[0]["trace_id"] == out["trace_id"]
        assert server[0]["parent_span_id"] == client[0]["span_id"]
        # queue-wait vs service-time attribution rides the server span
        annot = server[0].get("annot") or {}
        assert "queue_s" in annot and "service_s" in annot
        # waterfall decomposes: server span nested under the client span
        report = build_waterfall(buffer.get(out["trace_id"]))
        kinds = {s["kind"] for s in report["segments"]}
        assert {"queue", "service"} <= kinds
        assert report["critical_path"][0] == client[0]["span_id"]
    finally:
        srv.stop()
        gw.drain_and_stop(2.0)


def test_serve_shed_trace_retained_with_outcome(registry, buffer):
    from distar_tpu.serve import InferenceGateway, MockModelEngine

    eng = MockModelEngine(2, params={"version": "v1"})
    gw = InferenceGateway(eng).start()
    gw.load_version("v1", params={"version": "v1"}, activate=True)
    gw.begin_drain()  # every new request now sheds typed at the door
    obs_tree = {"x": np.zeros((2, 2), np.float32)}
    out = gw.act_many([{"session_id": "s", "obs": obs_tree,
                        "trace": wire_ctx(start_trace("caller"))}])
    from distar_tpu.serve.errors import DrainingError

    assert isinstance(out[0], DrainingError)
    # the draining fast path sheds before the per-request span is minted;
    # capacity/queue sheds DO retain spans — exercise via a full queue
    gw2 = InferenceGateway(MockModelEngine(1, params={"version": "v1"}),
                           queue_capacity=1)  # batcher NOT started: queue fills
    gw2.load_version("v1", params={"version": "v1"}, activate=True)
    gw2.act_many([{"session_id": "a", "obs": obs_tree}], timeout_s=0.01)
    shed = [r for r in buffer.records()
            if r["name"] == "serve_request" and r["outcome"] != "ok"]
    assert shed, "shed/timeout server spans must be retained"
    assert all(r["keep"] == "outcome" for r in shed)
    gw.drain_and_stop(1.0)


def test_replay_wire_propagation_tcp_and_limiter_annotation(registry, buffer):
    from distar_tpu.replay.client import InsertClient, SampleClient
    from distar_tpu.replay.errors import RateLimitTimeout
    from distar_tpu.replay.server import ReplayServer
    from distar_tpu.replay.store import ReplayStore, TableConfig
    from distar_tpu.resilience import RetryPolicy

    cfg = TableConfig(max_size=32, sampler="uniform", samples_per_insert=None,
                      min_size_to_sample=4)
    store = ReplayStore(table_factory=lambda n: cfg)
    srv = ReplayServer(store, transport="tcp").start()  # PR 11 note: pin tcp
    no_retry = RetryPolicy(max_attempts=1, backoff_base_s=0.01, deadline_s=5.0)
    try:
        with InsertClient(srv.host, srv.port, transport="tcp") as ic:
            ic.insert("t", {"x": 1})
        recs = buffer.records()
        ins_client = [r for r in recs if r["name"] == "replay_insert"
                      and "parent_span_id" not in r]
        ins_server = [r for r in recs if r["name"] == "replay_insert"
                      and "parent_span_id" in r]
        assert ins_client and ins_server
        assert ins_client[0]["trace_id"] == ins_server[0]["trace_id"]
        # a sample blocked by the limiter (min_size 4, one resident item)
        # times out typed — the trace is retained with the block attributed
        with SampleClient(srv.host, srv.port, transport="tcp",
                          retry_policy=no_retry) as sc:
            with pytest.raises(RateLimitTimeout):
                sc.sample("t", 1, timeout_s=0.25)
        shed_server = [r for r in buffer.records()
                       if r["name"] == "replay_sample" and "parent_span_id" in r]
        assert shed_server and shed_server[0]["outcome"] == "shed"
        blocked = (shed_server[0].get("annot") or {}).get("blocked_s", 0.0)
        assert blocked >= 0.2, f"limiter block not attributed: {blocked}"
        shed_client = [r for r in buffer.records()
                       if r["name"] == "replay_sample"
                       and "parent_span_id" not in r]
        assert shed_client and shed_client[0]["outcome"] == "shed"
    finally:
        srv.stop()


def test_replay_wire_propagation_over_shm(registry, buffer):
    from distar_tpu.replay.client import InsertClient
    from distar_tpu.replay.server import ReplayServer
    from distar_tpu.replay.store import ReplayStore, TableConfig

    cfg = TableConfig(max_size=32, sampler="uniform", samples_per_insert=None)
    store = ReplayStore(table_factory=lambda n: cfg)
    srv = ReplayServer(store, transport="auto").start()
    try:
        with InsertClient(srv.host, srv.port, transport="auto") as ic:
            ic.ping()  # dial + hello (connection is lazy)
            if ic.transport_active != "shm":
                pytest.skip("shm transport did not negotiate on this host")
            ic.insert("t", {"x": 2})
        recs = [r for r in buffer.records() if r["name"] == "replay_insert"]
        tids = {r["trace_id"] for r in recs}
        assert len(tids) == 1, "client+server spans must share one trace_id"
        assert any("parent_span_id" in r for r in recs), \
            "server span must join over the shm leg too"
    finally:
        srv.stop()


def test_traceparent_over_serve_http_frontend(registry, buffer):
    from distar_tpu.serve import InferenceGateway, MockModelEngine, ServeHTTPServer

    eng = MockModelEngine(2, params={"version": "v1"})
    gw = InferenceGateway(eng).start()
    gw.load_version("v1", params={"version": "v1"}, activate=True)
    http = ServeHTTPServer(gw).start()
    try:
        ctx = start_trace("http_caller")
        req = urllib.request.Request(
            f"http://{http.host}:{http.port}/serve/act",
            data=json.dumps({"session_id": "h1", "obs": {"x": [[0.0]]}}).encode(),
            headers={"Content-Type": "application/json",
                     "traceparent": format_traceparent(ctx)},
            method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            echoed = resp.headers.get("traceparent")
            body = json.loads(resp.read())
        assert body["code"] == 0
        # the response header echoes OUR trace_id with the server's span
        parsed = parse_traceparent(echoed)
        assert parsed and parsed["trace_id"] == ctx["trace_id"]
        assert body["trace_id"] == ctx["trace_id"]
        recs = buffer.records()
        http_span = [r for r in recs if r["name"] == "http_act"]
        gw_span = [r for r in recs if r["name"] == "serve_request"]
        assert http_span and http_span[0]["trace_id"] == ctx["trace_id"]
        assert http_span[0]["parent_span_id"] == ctx["span_id"]
        # the gateway span nests under the http frontend span
        assert gw_span and gw_span[0]["parent_span_id"] == http_span[0]["span_id"]
    finally:
        http.stop()
        gw.drain_and_stop(2.0)


def test_traceparent_over_coordinator_frontend(registry, buffer):
    from distar_tpu.comm.coordinator import CoordinatorServer

    srv = CoordinatorServer()
    srv.start()
    try:
        ctx = start_trace("broker_caller")
        req = urllib.request.Request(
            f"http://{srv.host}:{srv.port}/coordinator/stats",
            data=b"{}",
            headers={"Content-Type": "application/json",
                     "traceparent": format_traceparent(ctx)},
            method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            echoed = resp.headers.get("traceparent")
            assert json.loads(resp.read())["code"] == 0
        parsed = parse_traceparent(echoed)
        assert parsed and parsed["trace_id"] == ctx["trace_id"]
        recs = [r for r in buffer.records() if r["name"] == "coordinator_stats"]
        assert recs and recs[0]["parent_span_id"] == ctx["span_id"]
        # no header -> no span minted (legacy callers see zero change)
        before = len(buffer.records())
        req = urllib.request.Request(
            f"http://{srv.host}:{srv.port}/coordinator/stats", data=b"{}",
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.headers.get("traceparent") is None
            resp.read()
        assert len(buffer.records()) == before
    finally:
        srv.stop()


def test_tracing_disabled_is_off_everywhere(registry, buffer):
    from distar_tpu.serve import InferenceGateway, MockModelEngine

    prev = set_tracing(False)
    try:
        eng = MockModelEngine(2, params={"version": "v1"})
        gw = InferenceGateway(eng).start()
        gw.load_version("v1", params={"version": "v1"}, activate=True)
        out = gw.act("s", {"x": np.zeros((2, 2), np.float32)})
        assert "trace_id" not in out
        assert buffer.records() == []
        gw.drain_and_stop(1.0)
    finally:
        set_tracing(prev)


# ------------------------------------------------------ waterfall analyzer
def test_waterfall_decomposition_and_critical_path():
    t0 = 1000.0
    spans = [
        {"trace_id": "T", "span_id": "c", "name": "serve_client",
         "ts": t0, "dur_s": 0.100, "outcome": "ok", "hops": [],
         "source": "client"},
        {"trace_id": "T", "span_id": "g", "parent_span_id": "c",
         "name": "serve_request", "ts": t0 + 0.010, "dur_s": 0.080,
         "outcome": "ok", "hops": [],
         "annot": {"queue_s": 0.050, "service_s": 0.030}, "source": "gw"},
    ]
    report = build_waterfall(spans)
    assert report["trace_id"] == "T" and not report["skewed"]
    assert report["critical_path"] == ["c", "g"]
    seg = {(s["name"], s["kind"]): s["seconds"] for s in report["segments"]}
    assert seg[("serve_request", "queue")] == pytest.approx(0.050)
    assert seg[("serve_request", "service")] == pytest.approx(0.030)
    # the client's unexplained remainder (wire + untracked) is network/other
    assert seg[("serve_client", "network/other")] == pytest.approx(0.020, abs=1e-6)
    md = render_waterfall(report)
    assert "serve_request" in md and "critical path" in md
    # ranked: the largest segment first
    assert report["segments"][0]["kind"] == "queue"


def test_waterfall_flags_skewed_child():
    spans = [
        {"trace_id": "T", "span_id": "a", "name": "client", "ts": 100.0,
         "dur_s": 0.01, "outcome": "ok", "hops": []},
        # child claims to START before its parent: cross-host clock skew
        {"trace_id": "T", "span_id": "b", "parent_span_id": "a",
         "name": "server", "ts": 99.0, "dur_s": 0.005, "outcome": "ok",
         "hops": []},
    ]
    assert build_waterfall(spans)["skewed"] is True


# ----------------------------------------------------- loadgen trace links
def test_loadgen_summary_links_traces(registry, buffer, exemplars):
    sys.path.insert(0, "tools")
    try:
        from tools.loadgen import run_loadgen
    except ImportError:
        import loadgen as _lg

        run_loadgen = _lg.run_loadgen
    summary = run_loadgen(mode="closed", clients=2, duration_s=0.8,
                          requests_per_client=6, slots=4,
                          mock_delay_s=0.0, trace=True)
    slow = summary.get("slowest_traces")
    assert slow, "trace summary missing"
    # the named traces are retrievable from the local buffer (their root
    # spans were kept or their ids joined by retained server spans)
    all_tids = {r["trace_id"] for r in get_trace_buffer().records()}
    assert any(s["trace_id"] in all_tids for s in slow)


# ------------------------------------------------------------ E2E acceptance
def test_e2e_two_gateway_fleet_waterfall_and_exemplar_alert(
        registry, buffer, exemplars, recorder, tmp_path):
    """The acceptance drill: a 2-gateway fleet (REAL subprocesses), one
    gateway artificially slowed. Client spans (this process) and gateway
    spans (shipped over the telemetry channel) join under one trace_id in
    the coordinator trace store; ``opsctl trace`` retrieves the slow
    request's waterfall; the latency-SLO health rule fires with an exemplar
    trace_id that resolves via ``GET /trace/<id>``."""
    from distar_tpu.comm.coordinator import CoordinatorServer
    from distar_tpu.obs import HealthRule, init_fleet_health, set_fleet_health
    from distar_tpu.serve.fleet import FleetClient, GatewayMap

    prev_fleet = set_fleet_health(None)
    fleet_health = init_fleet_health(rules=[HealthRule(
        name="serve_latency_slo",
        metric="distar_serve_request_latency_seconds_p99",
        agg="last", op=">", threshold=0.01, window_s=120.0, for_count=2,
        summary="serving SLO breached",
    )], start=False, registry=registry)
    coord = CoordinatorServer()
    coord.start()
    caddr = f"{coord.host}:{coord.port}"
    procs, addrs = [], []
    try:
        for delay in (0.0, 0.03):  # gateway #2 is the slow one
            cmd = [sys.executable, "-m", "distar_tpu.serve.fleet.gateway_proc",
                   "--port", "0", "--http-port", "0", "--slots", "16",
                   "--mock-delay-s", str(delay), "--coordinator", caddr,
                   "--telemetry-interval-s", "0.5", "--lease-s", "60",
                   # drill posture: retain every span (the drill asserts
                   # RETRIEVAL; the sampler's keep/drop invariants have
                   # their own unit tests)
                   "--trace-keep-one-in", "1"]
            proc = subprocess.Popen(
                cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True,
                # the conftest exports DISTAR_TRACE=0 suite-wide; the
                # gateways under test trace
                env={**os.environ, "DISTAR_TRACE": "1"})
            parts = proc.stdout.readline().split()
            assert parts and parts[0] == "SERVE-GATEWAY", parts
            addrs.append(f"{parts[1]}:{parts[2]}")
            procs.append(proc)
        obs_tree = {"x": np.zeros((4, 4), np.float32)}
        fc = FleetClient(gateway_map=GatewayMap.parse(",".join(addrs)),
                         timeout_s=15.0)
        # drive sessions until both gateways served traffic (affinity is a
        # hash split; 24 distinct sessions cover 2 gateways w.h.p.); several
        # steps per session so the slow gateway's tail sampler has a
        # population to keep from, sessions ended to free their slots
        slow_tids, fast = [], 0
        for i in range(20):
            sid = f"e2e-{i}"
            for _step in range(2):
                t0 = time.perf_counter()
                out = fc.act(sid, obs_tree)
                dt = time.perf_counter() - t0
                if dt > 0.02:
                    slow_tids.append(out["trace_id"])
                else:
                    fast += 1
            fc.end(sid)
        assert slow_tids, "no slow requests observed against the slowed gateway"
        assert fast, "no fast requests — the un-slowed gateway served nothing"
        fc.close()
        # wait for both gateways to ship their tail-sampled spans
        deadline = time.time() + 20.0
        joined = None
        while time.time() < deadline and joined is None:
            for tid in slow_tids:
                spans = fleet_health.traces.get(tid)
                if spans:  # gateway-side span arrived over telemetry
                    joined = tid
                    break
            time.sleep(0.25)
        assert joined, "no slow trace's gateway span ever shipped"

        # --- the waterfall, via the coordinator's own HTTP surface
        with urllib.request.urlopen(
                f"http://{caddr}/trace/{joined}", timeout=10) as resp:
            body = json.loads(resp.read())
        names = {s["name"] for s in body["spans"]}
        assert "serve_client" in names and "serve_request" in names
        assert len({s["trace_id"] for s in body["spans"]}) == 1
        wf = body["waterfall"]
        kinds = {s["kind"] for s in wf["segments"]}
        assert "service" in kinds  # queue may be ~0 under light load
        gw_span = next(s for s in body["spans"] if s["name"] == "serve_request")
        assert "service_s" in (gw_span.get("annot") or {})

        # --- opsctl trace: list the slow traces, then render the waterfall
        env = {"PYTHONPATH": ".", "PATH": "/usr/bin:/bin:/usr/local/bin",
               "JAX_PLATFORMS": "cpu", "HOME": str(tmp_path)}
        listing = subprocess.run(
            [sys.executable, "tools/opsctl.py", "trace", "--addr", caddr,
             "--min-ms", "20", "--limit", "200"],
            capture_output=True, text=True, timeout=60, env=env)
        assert listing.returncode == 0, listing.stdout + listing.stderr
        assert joined in listing.stdout
        shown = subprocess.run(
            [sys.executable, "tools/opsctl.py", "trace", "--addr", caddr,
             "--id", joined],
            capture_output=True, text=True, timeout=60, env=env)
        assert shown.returncode == 0, shown.stdout + shown.stderr
        assert "serve_request" in shown.stdout
        assert "critical path" in shown.stdout

        # --- the SLO alert fires off SHIPPED telemetry, with an exemplar
        # (the slow gateway's p99 >> 10ms rides its registry snapshot).
        # Wait for an exemplar to arrive first: the ship that carried the
        # first kept trace may have snapshotted exemplars a beat before the
        # observe-side note — the next 0.5s ship closes the gap.
        from distar_tpu.obs import get_exemplar_store

        deadline = time.time() + 15.0
        while time.time() < deadline and get_exemplar_store().lookup(
                "distar_serve_request_latency_seconds_p99") is None:
            time.sleep(0.25)
        deadline = time.time() + 15.0
        firing = []
        while time.time() < deadline and not firing:
            events = fleet_health.evaluator.evaluate_once()
            firing = [e for e in events if e["state"] == "firing"]
            if not firing:
                time.sleep(0.5)
        assert firing, "latency SLO alert never fired off shipped telemetry"
        exemplar = firing[0].get("exemplar_trace_id")
        assert exemplar, "firing alert carries no exemplar trace_id"
        with urllib.request.urlopen(
                f"http://{caddr}/trace/{exemplar}", timeout=10) as resp:
            body = json.loads(resp.read())
        assert body["spans"], "exemplar trace_id did not resolve to spans"
    finally:
        for proc in procs:
            try:
                proc.stdin.close()
                proc.wait(timeout=10)
            except Exception:
                proc.kill()
        coord.stop()
        fleet_health.stop()
        set_fleet_health(prev_fleet)
