"""Model forward-mode tests on reduced shapes (full field schema, smaller
spatial map via config override) — mirrors the reference's fake_step_data
warmup contract (agent.py:120-127)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distar_tpu.lib import features as F
from distar_tpu.model import Model, default_model_config

B = 2


@pytest.fixture(scope="module")
def small_cfg():
    cfg = default_model_config()
    # shrink heavy dims for test speed; field schema stays complete
    cfg.encoder.entity.layer_num = 1
    cfg.encoder.entity.hidden_dim = 64
    cfg.encoder.entity.output_dim = 32
    cfg.encoder.entity.head_dim = 16
    cfg.encoder.spatial.down_channels = [8, 8, 16]
    cfg.encoder.spatial.project_dim = 8
    cfg.encoder.spatial.resblock_num = 1
    cfg.encoder.spatial.fc_dim = 32
    cfg.encoder.scatter.output_dim = 8
    cfg.encoder.core_lstm.hidden_size = 64
    cfg.encoder.core_lstm.num_layers = 2
    cfg.policy.action_type_head.res_dim = 32
    cfg.policy.action_type_head.res_num = 1
    cfg.policy.action_type_head.gate_dim = 64
    cfg.policy.delay_head.decode_dim = 32
    cfg.policy.queued_head.decode_dim = 32
    cfg.policy.selected_units_head.func_dim = 32
    cfg.policy.location_head.res_dim = 16
    cfg.policy.location_head.res_num = 1
    cfg.policy.location_head.upsample_dims = [8, 8, 1]
    cfg.policy.location_head.map_skip_dim = 16
    cfg.value.res_dim = 16
    cfg.value.res_num = 1
    cfg.use_value_network = True
    return cfg


def _batch_obs(n, train=False):
    obs = [F.fake_step_data(train=train, rng=np.random.default_rng(i)) for i in range(n)]
    batched = F.batch_tree(obs)
    return jax.tree.map(jnp.asarray, batched)


def _hidden(cfg, batch):
    H = cfg.encoder.core_lstm.hidden_size
    z = jnp.zeros((batch, H))
    return tuple((z, z) for _ in range(cfg.encoder.core_lstm.num_layers))


@pytest.fixture(scope="module")
def model_and_params(small_cfg):
    model = Model(small_cfg)
    # init through rl_forward: it traces encoder + teacher-forced policy +
    # every value tower, creating the complete parameter tree (the sampling
    # path shares all its params with the train path)
    T = 1
    data = _batch_obs((T + 1) * B)
    action_info = {
        "action_type": jnp.zeros((T, B), jnp.int32),
        "delay": jnp.zeros((T, B), jnp.int32),
        "queued": jnp.zeros((T, B), jnp.int32),
        "selected_units": jnp.zeros((T, B, F.MAX_SELECTED_UNITS_NUM), jnp.int32),
        "target_unit": jnp.zeros((T, B), jnp.int32),
        "target_location": jnp.zeros((T, B), jnp.int32),
    }
    sun = jnp.ones((T, B), jnp.int32)
    params = model.init(
        jax.random.PRNGKey(0),
        data["spatial_info"], data["entity_info"], data["scalar_info"], data["entity_num"],
        _hidden(small_cfg, B), action_info, sun, B, T,
        method=model.rl_forward,
    )
    return model, params


def test_sample_action_shapes(small_cfg, model_and_params):
    model, params = model_and_params
    data = _batch_obs(B)
    out = jax.jit(
        lambda p, d, h, r: model.apply(
            p, d["spatial_info"], d["entity_info"], d["scalar_info"], d["entity_num"], h, r,
            method=model.sample_action)
    )(params, data, _hidden(small_cfg, B), jax.random.PRNGKey(2))
    a = out["action_info"]
    assert a["action_type"].shape == (B,)
    assert a["selected_units"].shape == (B, F.MAX_SELECTED_UNITS_NUM)
    assert out["logit"]["selected_units"].shape == (B, 64, F.MAX_ENTITY_NUM + 1)
    assert out["logit"]["target_location"].shape == (B, 152 * 160)
    assert out["action_logp"]["selected_units"].shape == (B, 64)
    assert len(out["hidden_state"]) == small_cfg.encoder.core_lstm.num_layers
    # delays are in range
    assert int(a["delay"].max()) <= F.MAX_DELAY
    # selected_units_num <= 64
    assert int(out["selected_units_num"].max()) <= 64


def test_selected_units_respects_su_mask(small_cfg, model_and_params):
    """Sampled action types that don't select units must yield num == 0."""
    model, params = model_and_params
    data = _batch_obs(B)
    out = model.apply(
        params, data["spatial_info"], data["entity_info"], data["scalar_info"],
        data["entity_num"], _hidden(small_cfg, B), jax.random.PRNGKey(3),
        method=model.sample_action,
    )
    from distar_tpu.lib.actions import SELECTED_UNITS_MASK

    su = np.asarray(SELECTED_UNITS_MASK)[np.asarray(out["action_info"]["action_type"])]
    num = np.asarray(out["selected_units_num"])
    assert (num[~su] == 0).all()


def test_rl_forward_shapes(small_cfg, model_and_params):
    model, params = model_and_params
    T = 3
    n = (T + 1) * B
    data = _batch_obs(n, train=False)
    action_info = {
        "action_type": jnp.zeros((T, B), jnp.int32),
        "delay": jnp.zeros((T, B), jnp.int32),
        "queued": jnp.zeros((T, B), jnp.int32),
        "selected_units": jnp.zeros((T, B, F.MAX_SELECTED_UNITS_NUM), jnp.int32),
        "target_unit": jnp.zeros((T, B), jnp.int32),
        "target_location": jnp.zeros((T, B), jnp.int32),
    }
    sun = jnp.full((T, B), 2, jnp.int32)
    out = model.apply(
        params,
        data["spatial_info"], data["entity_info"], data["scalar_info"], data["entity_num"],
        _hidden(small_cfg, B), action_info, sun, B, T,
        method=model.rl_forward,
    )
    assert out["target_logit"]["action_type"].shape == (T, B, 327)
    assert out["target_logit"]["selected_units"].shape == (T, B, 64, 513)
    for k, v in out["value"].items():
        assert v.shape == (T + 1, B), k
    # winloss squashed into (-1, 1)
    assert np.abs(np.asarray(out["value"]["winloss"])).max() < 1.0


def test_teacher_and_sl_forward(small_cfg, model_and_params):
    model, params = model_and_params
    data = _batch_obs(B)
    action_info = {
        "action_type": jnp.zeros((B,), jnp.int32),
        "delay": jnp.zeros((B,), jnp.int32),
        "queued": jnp.zeros((B,), jnp.int32),
        "selected_units": jnp.zeros((B, F.MAX_SELECTED_UNITS_NUM), jnp.int32),
        "target_unit": jnp.zeros((B,), jnp.int32),
        "target_location": jnp.zeros((B,), jnp.int32),
    }
    sun = jnp.ones((B,), jnp.int32)
    out = model.apply(
        params, data["spatial_info"], data["entity_info"], data["scalar_info"],
        data["entity_num"], _hidden(small_cfg, B), action_info, sun,
        method=model.teacher_logits,
    )
    assert out["logit"]["action_type"].shape == (B, 327)

    # SL: batch of 1 trajectory x T=2 steps
    T = 2
    data2 = _batch_obs(T)  # B=1 trajectory of len 2 flat
    logits, state = model.apply(
        params, data2["spatial_info"], data2["entity_info"], data2["scalar_info"],
        data2["entity_num"],
        {k: jnp.repeat(v, 1, axis=0) for k, v in {
            "action_type": jnp.zeros((T,), jnp.int32),
            "delay": jnp.zeros((T,), jnp.int32),
            "queued": jnp.zeros((T,), jnp.int32),
            "selected_units": jnp.zeros((T, F.MAX_SELECTED_UNITS_NUM), jnp.int32),
            "target_unit": jnp.zeros((T,), jnp.int32),
            "target_location": jnp.zeros((T,), jnp.int32),
        }.items()},
        jnp.full((T,), 1, jnp.int32),
        _hidden(small_cfg, 1), 1,
        method=model.sl_forward,
    )
    assert logits["action_type"].shape == (T, 327)
    assert len(state) == small_cfg.encoder.core_lstm.num_layers


def test_bfloat16_compute_dtype(small_cfg, model_and_params):
    """cfg.dtype='bfloat16' must produce finite float32 outputs (params stay
    f32; matmuls/convs compute in bf16 on the MXU)."""
    model, params = model_and_params
    from distar_tpu.utils import deep_merge_dicts

    bf_cfg = deep_merge_dicts(small_cfg, {"dtype": "bfloat16"})
    bf_model = Model(bf_cfg)
    data = _batch_obs(B)
    out = bf_model.apply(
        params, data["spatial_info"], data["entity_info"], data["scalar_info"],
        data["entity_num"], _hidden(small_cfg, B), jax.random.PRNGKey(5),
        method=bf_model.sample_action,
    )
    for k, v in out["logit"].items():
        assert np.isfinite(np.asarray(v, dtype=np.float32)).all(), k
    # params remain float32
    assert jax.tree.leaves(params)[0].dtype == jnp.float32


def test_su_head_parallel_matches_scan(small_cfg, model_and_params):
    """The batched teacher-forced SelectedUnits path must equal the scan path
    bit-for-bit in semantics: same logits on real steps, same downstream
    embeddings (checked via target_unit/location logits)."""
    from distar_tpu.utils import deep_merge_dicts

    model, params = model_and_params
    scan_cfg = deep_merge_dicts(
        small_cfg, {"policy": {"selected_units_head": {"train_impl": "scan"}}}
    )
    scan_model = Model(scan_cfg)
    data = _batch_obs(B)
    rng = np.random.default_rng(7)
    labels = np.zeros((B, F.MAX_SELECTED_UNITS_NUM), np.int64)
    sun = np.array([3, 5])
    for b in range(B):
        labels[b, : sun[b] - 1] = rng.permutation(6)[: sun[b] - 1]
        labels[b, sun[b] - 1] = int(data["entity_num"][b])  # end token
    action_info = {
        "action_type": jnp.zeros((B,), jnp.int32),
        "delay": jnp.zeros((B,), jnp.int32),
        "queued": jnp.zeros((B,), jnp.int32),
        "selected_units": jnp.asarray(labels),
        "target_unit": jnp.zeros((B,), jnp.int32),
        "target_location": jnp.zeros((B,), jnp.int32),
    }
    outs = {}
    for name, m in (("parallel", model), ("scan", scan_model)):
        outs[name] = m.apply(
            params, data["spatial_info"], data["entity_info"], data["scalar_info"],
            data["entity_num"], _hidden(small_cfg, B), action_info, jnp.asarray(sun),
            method=m.teacher_logits,
        )
    su_p = np.asarray(outs["parallel"]["logit"]["selected_units"])
    su_s = np.asarray(outs["scan"]["logit"]["selected_units"])
    # compare real steps only (post-end steps diverge in masking, loss-masked)
    for b in range(B):
        np.testing.assert_allclose(
            su_p[b, : sun[b]], su_s[b, : sun[b]], rtol=2e-4, atol=2e-4
        )
    # downstream heads see the same autoregressive embedding
    for head in ("target_unit", "target_location"):
        np.testing.assert_allclose(
            np.asarray(outs["parallel"]["logit"][head]),
            np.asarray(outs["scan"]["logit"][head]),
            rtol=2e-4, atol=2e-4,
        )


def test_scan_unroll_knobs_preserve_numerics(small_cfg, model_and_params):
    """core_lstm/selected_units_head scan_unroll are pure scheduling knobs:
    sample-mode outputs on identical params must match the defaults."""
    from distar_tpu.utils import deep_merge_dicts

    model, params = model_and_params
    unrolled = Model(deep_merge_dicts(
        small_cfg,
        {"encoder": {"core_lstm": {"scan_unroll": 4}},
         "policy": {"selected_units_head": {"scan_unroll": 8}}},
    ))
    data = _batch_obs(B)
    outs = {}
    for name, m in (("base", model), ("unrolled", unrolled)):
        outs[name] = m.apply(
            params, data["spatial_info"], data["entity_info"], data["scalar_info"],
            data["entity_num"], _hidden(small_cfg, B), jax.random.PRNGKey(3),
            method=m.sample_action,
        )
    for head, a in outs["base"]["logit"].items():
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(outs["unrolled"]["logit"][head]),
            rtol=2e-5, atol=2e-5, err_msg=head,
        )
    np.testing.assert_array_equal(
        np.asarray(outs["base"]["action_info"]["selected_units"]),
        np.asarray(outs["unrolled"]["action_info"]["selected_units"]),
    )


def test_remat_preserves_numerics(rng):
    """cfg.remat wraps the activation-heavy blocks in jax.checkpoint: the
    HBM-for-FLOPs knob must not change forward or gradient numerics."""
    import jax
    import jax.numpy as jnp

    from distar_tpu.lib import features as F
    from distar_tpu.model import Model, default_model_config
    from distar_tpu.utils import deep_merge_dicts

    small = {
        "encoder": {
            "entity": {"layer_num": 1, "hidden_dim": 32, "output_dim": 16, "head_dim": 8},
            "spatial": {"down_channels": [4, 4, 8], "project_dim": 4, "resblock_num": 1, "fc_dim": 16},
            "scatter": {"output_dim": 4},
            "core_lstm": {"hidden_size": 32, "num_layers": 1},
        },
        "policy": {
            "action_type_head": {"res_dim": 16, "res_num": 1, "gate_dim": 32},
            "delay_head": {"decode_dim": 16},
            "queued_head": {"decode_dim": 16},
            "selected_units_head": {"func_dim": 16},
            "target_unit_head": {"func_dim": 16},
            "location_head": {"res_dim": 8, "res_num": 1, "upsample_dims": [4, 4, 1], "map_skip_dim": 8},
        },
        "value": {"res_dim": 8, "res_num": 1},
    }
    B = 2
    obs = F.batch_tree([F.fake_step_data(train=False, rng=rng) for _ in range(B)])
    obs = jax.tree.map(jnp.asarray, obs)

    outs = {}
    params = None
    for remat in (False, True):
        cfg = deep_merge_dicts(default_model_config(), dict(small, remat=remat))
        model = Model(cfg)
        H = cfg.encoder.core_lstm.hidden_size
        hidden = tuple(
            (jnp.zeros((B, H)), jnp.zeros((B, H)))
            for _ in range(cfg.encoder.core_lstm.num_layers)
        )
        if params is None:
            params = model.init(
                jax.random.PRNGKey(0),
                obs["spatial_info"], obs["entity_info"], obs["scalar_info"],
                obs["entity_num"], hidden, jax.random.PRNGKey(1),
                method=model.sample_action,
            )

        def loss(p):
            out = model.apply(
                p, obs["spatial_info"], obs["entity_info"], obs["scalar_info"],
                obs["entity_num"], hidden, jax.random.PRNGKey(1),
                method=model.sample_action,
            )
            return sum(jnp.sum(l.astype(jnp.float32) ** 2) for l in jax.tree.leaves(out["logit"]))

        val, grad = jax.jit(jax.value_and_grad(loss))(params)
        outs[remat] = (val, grad)

    v0, g0 = outs[False]
    v1, g1 = outs[True]
    assert jnp.allclose(v0, v1, rtol=1e-5), (v0, v1)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        assert jnp.allclose(a, b, rtol=1e-4, atol=1e-5)
