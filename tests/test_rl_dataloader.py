"""RL dataloader coverage: `collate_trajectories` edge paths, the typed
`CollationError`, and the condition-variable wait that replaced the 5 ms
busy-poll (with its `distar_dataloader_wait_s` starvation histogram)."""
import threading
import time

import numpy as np
import pytest

from distar_tpu.learner.rl_dataloader import (
    CollationError,
    RLDataLoader,
    ReplayDataLoader,
    collate_trajectories,
)
from distar_tpu.lib import features as F
from distar_tpu.obs import MetricsRegistry, set_registry

T = 2
B = 3
HIDDEN = 4


def tiny_step(t: int, sun: int = 2, value_feature: bool = False) -> dict:
    """Schema-minimal trajectory step: every key collate touches, with toy
    shapes (full-schema collation is covered by the pipeline tests)."""
    step = {
        "spatial_info": {"height_map": np.full((2, 2), t, np.float32)},
        "entity_info": {"x": np.zeros((3, 2), np.float32)},
        "scalar_info": {"s": np.asarray(float(t), np.float32)},
        "entity_num": np.asarray(3, np.int64),
        "hidden_state": (
            (np.zeros(HIDDEN, np.float32), np.zeros(HIDDEN, np.float32)),
        ),
        "action_info": {"action_type": np.asarray(t, np.int64)},
        "selected_units_num": np.asarray(sun, np.int64),
        "behaviour_logp": {"action_type": np.asarray(-0.5, np.float32)},
        "teacher_logit": {"action_type": np.zeros(5, np.float32)},
        "reward": np.asarray(0.25, np.float32),
        "step": np.asarray(t, np.int64),
        "mask": {"actions": np.asarray(1.0, np.float32)},
    }
    if value_feature:
        step["value_feature"] = {"vf": np.full((2,), t, np.float32)}
    return step


def tiny_traj(sun=2, done=False, value_feature=False, length=T + 1):
    traj = [tiny_step(t, sun=sun, value_feature=value_feature)
            for t in range(length)]
    if done:
        for s in traj[:-1]:
            s["done"] = np.asarray(1.0, np.float32)
    traj[0]["model_last_iter"] = 7.0
    return traj


# ------------------------------------------------------------------- collate
def test_collate_missing_done_defaults_to_zero():
    batch = collate_trajectories([tiny_traj() for _ in range(B)])
    assert batch["done"].shape == (T, B)
    assert np.all(batch["done"] == 0.0)
    # explicit done flows through untouched
    batch2 = collate_trajectories([tiny_traj(done=True) for _ in range(B)])
    assert np.all(batch2["done"] == 1.0)


def test_collate_value_feature_branch():
    with_vf = collate_trajectories([tiny_traj(value_feature=True) for _ in range(B)])
    assert with_vf["value_feature"]["vf"].shape == (T + 1, B, 2)
    without = collate_trajectories([tiny_traj() for _ in range(B)])
    assert "value_feature" not in without


def test_collate_selected_units_mask_matches_counts():
    suns = [0, 3, F.MAX_SELECTED_UNITS_NUM]
    batch = collate_trajectories([tiny_traj(sun=s) for s in suns])
    mask = batch["mask"]["selected_units_mask"]
    assert mask.shape == (T, len(suns), F.MAX_SELECTED_UNITS_NUM)
    for b, sun in enumerate(suns):
        assert mask[:, b, :sun].all()
        assert not mask[:, b, sun:].any()
    assert batch["model_last_iter"].tolist() == [7.0] * len(suns)


def test_collate_time_major_layout():
    batch = collate_trajectories([tiny_traj() for _ in range(B)])
    assert batch["spatial_info"]["height_map"].shape == (T + 1, B, 2, 2)
    assert batch["reward"].shape == (T, B)
    h, c = batch["hidden_state"][0]
    assert h.shape == (B, HIDDEN) and c.shape == (B, HIDDEN)


def test_collation_error_carries_lengths_and_is_typed():
    trajs = [tiny_traj(), tiny_traj(length=T + 2), tiny_traj()]
    with pytest.raises(CollationError) as e:
        collate_trajectories(trajs)
    assert e.value.lengths == [T + 1, T + 2, T + 1]
    assert isinstance(e.value, ValueError)  # legacy except-clauses still catch
    with pytest.raises(CollationError) as e2:
        collate_trajectories([])
    assert e2.value.lengths == []
    with pytest.raises(CollationError):
        collate_trajectories([[tiny_step(0)]])  # bootstrap-only: T == 0


# ------------------------------------------------- condition-variable wait
@pytest.fixture
def fresh_registry():
    reg = MetricsRegistry()
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


def test_next_waits_on_condition_and_records_starvation(fresh_registry):
    from distar_tpu.comm import Adapter, Coordinator

    co = Coordinator()
    producer = Adapter(coordinator=co)
    consumer = Adapter(coordinator=co)
    loader = RLDataLoader(consumer, "MP0", batch_size=1, cache_size=4)

    def push_later():
        time.sleep(0.3)
        producer.push("MP0traj", tiny_traj(), timeout_ms=30_000)

    threading.Thread(target=push_later, daemon=True).start()
    t0 = time.monotonic()
    batch = next(loader)
    elapsed = time.monotonic() - t0
    assert batch["reward"].shape == (T, 1)
    assert elapsed >= 0.25  # it really blocked, not spun through an empty cache
    hist = fresh_registry.histogram("distar_dataloader_wait_s", token="MP0traj")
    assert hist.count == 1
    assert hist.sum >= 0.2  # the starvation window landed in the histogram
    consumer.stop()
    producer.stop()


def test_next_does_not_wait_when_cache_is_hot(fresh_registry):
    from distar_tpu.comm import Adapter, Coordinator

    co = Coordinator()
    producer = Adapter(coordinator=co)
    consumer = Adapter(coordinator=co)
    for _ in range(2):
        producer.push("MP0traj", tiny_traj(), timeout_ms=30_000)
    loader = RLDataLoader(consumer, "MP0", batch_size=2, cache_size=4)
    deadline = time.monotonic() + 10.0
    while loader.buffered() < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    batch = next(loader)
    assert batch["reward"].shape == (T, 2)
    hist = fresh_registry.histogram("distar_dataloader_wait_s", token="MP0traj")
    assert hist.count == 1 and hist.quantile(0.99) < 0.5
    consumer.stop()
    producer.stop()


# ------------------------------------------------------ store-backed loader
def test_replay_dataloader_feeds_same_collate(fresh_registry):
    from distar_tpu.replay import (
        InsertClient, ReplayServer, ReplayStore, SampleClient, TableConfig,
    )

    store = ReplayStore(table_factory=lambda n: TableConfig(
        max_size=16, sampler="uniform", samples_per_insert=None,
        min_size_to_sample=1))
    server = ReplayServer(store, port=0).start()
    try:
        ic = InsertClient(server.host, server.port)
        for _ in range(3):
            ic.insert("MP0", tiny_traj())
        loader = ReplayDataLoader(
            SampleClient(server.host, server.port), "MP0", batch_size=2)
        assert loader.token == "MP0"
        batch = next(loader)
        assert batch["reward"].shape == (T, 2)
        assert batch["spatial_info"]["height_map"].shape == (T + 1, 2, 2, 2)
        assert len(loader.last_sample_info) == 2
        assert {"seq", "sample_count", "staleness_s"} <= set(loader.last_sample_info[0])
        assert loader.update_priorities({0: 9.0}) <= 1
        ic.close()
        loader._client.close()
    finally:
        server.stop()
