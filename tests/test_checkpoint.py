"""Checkpoint save/load + the async writer (previously covered only
indirectly through learner/pipeline tests)."""
import os
import threading

import numpy as np

from distar_tpu.utils.checkpoint import (
    AsyncCheckpointer,
    load_checkpoint,
    save_checkpoint,
)


def _gated_writer(monkeypatch):
    """Monkeypatch the module-level writer behind an Event gate; returns the
    gate so a test can hold the write pending deterministically."""
    from distar_tpu.utils import checkpoint as ckpt_mod

    gate = threading.Event()
    real = ckpt_mod._write_checkpoint

    def gated(path, host_state, metadata):
        assert gate.wait(10), "test gate never opened"
        return real(path, host_state, metadata)

    monkeypatch.setattr(ckpt_mod, "_write_checkpoint", gated)
    return gate


def _state(v=1.0):
    return {"params": {"w": np.full((4, 4), v), "b": np.zeros(4)},
            "step": np.asarray(3)}


def test_roundtrip(tmp_path):
    path = str(tmp_path / "c.ckpt")
    save_checkpoint(path, _state(2.0), metadata={"last_iter": 7})
    out = load_checkpoint(path)
    assert out["metadata"]["last_iter"] == 7
    np.testing.assert_array_equal(out["state"]["params"]["w"], np.full((4, 4), 2.0))


def test_partial_restore_keeps_missing_and_drops_extra(tmp_path):
    path = str(tmp_path / "c.ckpt")
    save_checkpoint(path, {"params": {"w": np.ones(2), "legacy": np.zeros(1)}})
    target = {"params": {"w": np.zeros(2), "new_head": np.full(3, 9.0)}}
    out = load_checkpoint(path, target=target)
    np.testing.assert_array_equal(out["state"]["params"]["w"], np.ones(2))
    # missing leaf keeps the target's value; the checkpoint's extra is dropped
    np.testing.assert_array_equal(out["state"]["params"]["new_head"], np.full(3, 9.0))
    assert "legacy" not in out["state"]["params"]


def test_async_checkpointer_roundtrip_and_ordering(tmp_path):
    path = str(tmp_path / "a.ckpt")
    ck = AsyncCheckpointer()
    # back-to-back saves: the second must observe the first's completion
    ck.save(path, _state(1.0), metadata={"last_iter": 1})
    ck.save(path, _state(5.0), metadata={"last_iter": 2})
    ck.wait()
    out = load_checkpoint(path)
    assert out["metadata"]["last_iter"] == 2
    np.testing.assert_array_equal(out["state"]["params"]["w"], np.full((4, 4), 5.0))
    ck.wait()  # idempotent


def test_async_checkpointer_snapshots_before_mutation(tmp_path, monkeypatch):
    """save() must COPY to host before returning: mutating the source array
    afterwards must not corrupt the written checkpoint (np.asarray would
    alias the live buffer — the donated-buffer corruption this API exists
    to prevent). The writer is gated so the mutation deterministically
    happens while the write is still pending."""
    gate = _gated_writer(monkeypatch)
    path = str(tmp_path / "m.ckpt")
    ck = AsyncCheckpointer()
    live = {"w": np.ones(8)}
    ck.save(path, live)
    live["w"][:] = -1.0  # the 'next train step' reusing the buffer
    gate.set()
    ck.wait()
    out = load_checkpoint(path)
    np.testing.assert_array_equal(out["state"]["w"], np.ones(8))


def test_async_checkpointer_overlaps_writer(tmp_path, monkeypatch):
    """The writer runs off-thread: save() returns while the (gated) write
    is still pending, and wait() observes its completion."""
    gate = _gated_writer(monkeypatch)
    wrote = []
    from distar_tpu.utils import checkpoint as ckpt_mod

    inner = ckpt_mod._write_checkpoint  # the gated wrapper

    def recording(path, host_state, metadata):
        r = inner(path, host_state, metadata)
        wrote.append(path)
        return r

    monkeypatch.setattr(ckpt_mod, "_write_checkpoint", recording)
    path = str(tmp_path / "big.ckpt")
    ck = AsyncCheckpointer()
    ck.save(path, _state(3.0))
    # save() returned while the writer is blocked on the gate: true overlap
    assert wrote == [] and not os.path.exists(path)
    gate.set()
    ck.wait()
    assert wrote == [path] and os.path.exists(path)


def test_async_checkpointer_surfaces_writer_errors(tmp_path, monkeypatch):
    """A failed background write must raise loudly at the next wait()/save(),
    never be silently swallowed (a learner believing checkpoints exist)."""
    import pytest

    from distar_tpu.utils import checkpoint as ckpt_mod

    def boom(path, host_state, metadata):
        raise OSError("disk full")

    monkeypatch.setattr(ckpt_mod, "_write_checkpoint", boom)
    path = str(tmp_path / "fail.ckpt")
    ck = AsyncCheckpointer()
    ck.save(path, _state())
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        ck.wait()
    # the error is consumed: the checkpointer is usable again
    monkeypatch.setattr(ckpt_mod, "_write_checkpoint", lambda p, s, m: None)
    ck.save(path, _state())
    ck.wait()
