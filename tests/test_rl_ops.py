"""Golden tests for return/advantage primitives against plain-numpy oracles
written directly from the recursions (Sutton & Barto 12.18; IMPALA eq. 1)."""
import numpy as np
import jax.numpy as jnp

from distar_tpu.ops import (
    generalized_lambda_returns,
    td_lambda_loss,
    upgo_returns,
    vtrace_advantages,
)

T, B = 7, 3


def np_lambda_returns(r, gamma, v_tp1, lam):
    # v_tp1: [T, B] = V[1..T]; G[t] = r[t] + gamma*(lam*G[t+1] + (1-lam)*V[t+1])
    Tn = r.shape[0]
    out = np.zeros_like(r)
    out[-1] = r[-1] + gamma[-1] * v_tp1[-1]
    for t in range(Tn - 2, -1, -1):
        out[t] = r[t] + gamma[t] * (lam[t] * out[t + 1] + (1 - lam[t]) * v_tp1[t])
    return out


def np_vtrace(rhos, cs, r, v, gamma, lam):
    Tn = r.shape[0]
    deltas = rhos * (r + gamma * v[1:] - v[:-1])
    vs = np.zeros_like(v)
    vs[-1] = v[-1]
    for t in range(Tn - 1, -1, -1):
        vs[t] = v[t] + deltas[t] + gamma * lam * cs[t] * (vs[t + 1] - v[t + 1])
    return rhos * (r + gamma * vs[1:] - v[:-1])


def _rand(rng, *shape):
    return rng.standard_normal(shape).astype(np.float32)


def test_generalized_lambda_returns(rng):
    r = _rand(rng, T, B)
    v = _rand(rng, T + 1, B)
    gamma, lam = 0.9, 0.8
    got = generalized_lambda_returns(jnp.asarray(r), gamma, jnp.asarray(v), lam)
    want = np_lambda_returns(r, np.full((T, B), gamma), v[1:], np.full((T, B), lam))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_td_lambda_loss_matches_manual(rng):
    r = _rand(rng, T, B)
    v = _rand(rng, T + 1, B)
    got = float(td_lambda_loss(jnp.asarray(v), jnp.asarray(r), 1.0, 0.8))
    returns = np_lambda_returns(r, np.ones((T, B)), v[1:], np.full((T, B), 0.8))
    want = float((0.5 * (returns - v[:-1]) ** 2).mean())
    assert abs(got - want) < 1e-5


def test_td_lambda_mask(rng):
    r = _rand(rng, T, B)
    v = _rand(rng, T + 1, B)
    mask = np.zeros((T, B), np.float32)
    assert float(td_lambda_loss(jnp.asarray(v), jnp.asarray(r), mask=jnp.asarray(mask))) == 0.0


def test_upgo_returns(rng):
    r = _rand(rng, T, B)
    v = _rand(rng, T + 1, B)
    got = np.asarray(upgo_returns(jnp.asarray(r), jnp.asarray(v)))
    lambdas = ((r + v[1:]) >= v[:-1]).astype(np.float32)
    lambdas = np.concatenate([lambdas[1:], np.ones_like(lambdas[-1:])], axis=0)
    want = np_lambda_returns(r, np.ones((T, B)), v[1:], lambdas)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_vtrace_advantages(rng):
    r = _rand(rng, T, B)
    v = _rand(rng, T + 1, B)
    rhos = np.clip(np.exp(_rand(rng, T, B)), None, 1.0).astype(np.float32)
    got = np.asarray(
        vtrace_advantages(jnp.asarray(rhos), jnp.asarray(rhos), jnp.asarray(r), jnp.asarray(v),
                          gammas=1.0, lambda_=0.8)
    )
    want = np_vtrace(rhos, rhos, r, v, 1.0, 0.8)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_vtrace_on_policy_reduces_to_lambda_advantage(rng):
    # with rhos == cs == 1 and lambda=1, vtrace target == full return
    r = _rand(rng, T, B)
    v = _rand(rng, T + 1, B)
    ones = np.ones((T, B), np.float32)
    adv = np.asarray(
        vtrace_advantages(jnp.asarray(ones), jnp.asarray(ones), jnp.asarray(r), jnp.asarray(v),
                          gammas=1.0, lambda_=1.0)
    )
    # oracle: G_t = sum_{s>=t} r_s + V_T; adv = G_t - V_t
    G = np.zeros_like(r)
    acc = v[-1]
    for t in range(T - 1, -1, -1):
        acc = r[t] + acc
        G[t] = acc
    np.testing.assert_allclose(adv, G - v[:-1], rtol=1e-4, atol=1e-4)
