"""Ring attention over the sp mesh axis vs single-device full attention."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distar_tpu.ops.pallas_kernels import masked_attention_reference
from distar_tpu.parallel import MeshSpec, make_mesh
from distar_tpu.parallel.ring_attention import ring_self_attention


@pytest.mark.parametrize("spec", [MeshSpec(dp=1, sp=8), MeshSpec(dp=2, sp=4)])
def test_ring_attention_exact(rng, spec):
    mesh = make_mesh(spec)
    B, H, N, D = 2, 2, 64, 16
    q = jnp.asarray(rng.standard_normal((B, H, N, D)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((B, H, N, D)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((B, H, N, D)).astype(np.float32))
    mask = jnp.asarray(rng.random((B, N)) > 0.3)
    # ensure at least one valid key per batch
    mask = mask.at[:, 0].set(True)
    with mesh:
        got = ring_self_attention(q, k, v, mask, mesh)
    want = masked_attention_reference(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_ring_attention_long_sequence(rng):
    """Sequence 8x longer than any single shard's block."""
    mesh = make_mesh(MeshSpec(dp=1, sp=8))
    B, H, N, D = 1, 1, 1024, 8
    q = jnp.asarray(rng.standard_normal((B, H, N, D)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((B, H, N, D)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((B, H, N, D)).astype(np.float32))
    with mesh:
        got = ring_self_attention(q, k, v, None, mesh)
    want = masked_attention_reference(q, k, v, jnp.ones((B, N), bool))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)
