"""Ring attention over the sp mesh axis vs single-device full attention."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distar_tpu.ops.pallas_kernels import masked_attention_reference
from distar_tpu.parallel import MeshSpec, make_mesh
from distar_tpu.parallel.ring_attention import ring_self_attention


@pytest.mark.parametrize("spec", [MeshSpec(dp=1, sp=8), MeshSpec(dp=2, sp=4)])
def test_ring_attention_exact(rng, spec):
    mesh = make_mesh(spec)
    B, H, N, D = 2, 2, 64, 16
    q = jnp.asarray(rng.standard_normal((B, H, N, D)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((B, H, N, D)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((B, H, N, D)).astype(np.float32))
    mask = jnp.asarray(rng.random((B, N)) > 0.3)
    # ensure at least one valid key per batch
    mask = mask.at[:, 0].set(True)
    with mesh:
        got = ring_self_attention(q, k, v, mask, mesh)
    want = masked_attention_reference(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_ring_attention_long_sequence(rng):
    """Sequence 8x longer than any single shard's block."""
    mesh = make_mesh(MeshSpec(dp=1, sp=8))
    B, H, N, D = 1, 1, 1024, 8
    q = jnp.asarray(rng.standard_normal((B, H, N, D)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((B, H, N, D)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((B, H, N, D)).astype(np.float32))
    with mesh:
        got = ring_self_attention(q, k, v, None, mesh)
    want = masked_attention_reference(q, k, v, jnp.ones((B, N), bool))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_attention_module_ring_impl_matches_xla(rng):
    """impl='ring' on ops.Attention: same params, same output as the dense
    XLA path, with the set axis sharded over the context mesh's sp axis —
    the integration point the learner enables via encoder.entity.attention_impl."""
    from distar_tpu.ops.transformer import Attention
    from distar_tpu.parallel import set_context_mesh

    mesh = make_mesh(MeshSpec(dp=2, fsdp=1, sp=4))
    x = jnp.asarray(rng.standard_normal((2, 32, 24)).astype(np.float32))
    mask = jnp.asarray(rng.random((2, 32)) > 0.3).at[:, 0].set(True)
    ring = Attention(head_dim=8, head_num=2, output_dim=24, impl="ring")
    xla = Attention(head_dim=8, head_num=2, output_dim=24, impl="xla")
    try:
        set_context_mesh(mesh)
        params = ring.init(jax.random.PRNGKey(0), x, mask)
        compiled = jax.jit(ring.apply).lower(params, x, mask).compile()
        assert "collective-permute" in compiled.as_text()
        got = compiled(params, x, mask)
        # gradients flow through the ring (ppermute transpose)
        g = jax.grad(lambda p: jnp.sum(ring.apply(p, x, mask) ** 2))(params)
        assert all(bool(jnp.any(leaf != 0)) for leaf in jax.tree.leaves(g))
    finally:
        set_context_mesh(None)
    want = jax.jit(xla.apply)(params, x, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_attention_ring_impl_falls_back_without_mesh(rng):
    from distar_tpu.ops.transformer import Attention
    from distar_tpu.parallel import set_context_mesh

    set_context_mesh(None)
    x = jnp.asarray(rng.standard_normal((2, 16, 24)).astype(np.float32))
    ring = Attention(head_dim=8, head_num=2, output_dim=24, impl="ring")
    params = ring.init(jax.random.PRNGKey(0), x, None)
    out = jax.jit(ring.apply)(params, x, None)
    assert out.shape == (2, 16, 24)


def test_param_sharding_tp_rules(rng):
    """Megatron placement: Attention QKV kernel shards its output (head) dim
    over tp, the output projection shards its input dim; fsdp lands on a
    different dim than tp."""
    from distar_tpu.ops.transformer import Transformer
    from distar_tpu.parallel import param_sharding

    mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
    model = Transformer(head_dim=8, hidden_dim=32, output_dim=16, head_num=2,
                        mlp_num=2, layer_num=1)
    x = jnp.asarray(rng.standard_normal((2, 8, 16)).astype(np.float32))
    params = model.init(jax.random.PRNGKey(0), x, None)
    shardings = param_sharding(mesh, params)
    flat = jax.tree_util.tree_flatten_with_path(shardings)[0]
    by_path = {"/".join(p.key for p in path): s.spec for path, s in flat}
    qkv = next(v for k, v in by_path.items() if "Attention_0/Dense_0/kernel" in k)
    out_proj = next(v for k, v in by_path.items() if "Attention_0/Dense_1/kernel" in k)
    assert qkv[1] == "tp" and qkv[0] == "fsdp", qkv
    assert out_proj[0] == "tp", out_proj
    # every tp dim differs from the fsdp dim on every leaf
    for spec in by_path.values():
        axes = [a for a in spec if a is not None]
        assert len(axes) == len(set(axes))
