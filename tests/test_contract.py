"""Contract tests: derived action tables must match the reference's published
counts (actor_critic_default_config.yaml:1-11) and internal consistency."""
import numpy as np

from distar_tpu.lib import actions as A
from distar_tpu.lib import features as F


def test_vocabulary_sizes():
    assert A.NUM_ACTIONS == 327
    assert A.NUM_UNIT_TYPES == 260
    assert A.NUM_BUFFS == 50
    assert A.NUM_UPGRADES == 90
    assert A.NUM_ADDON == 9
    assert A.NUM_UNIT_MIX_ABILITIES == 269


def test_derived_action_subset_sizes():
    # reference: actor_critic_default_config.yaml:6-8. NB the reference yaml
    # says NUM_QUEUE_ACTIONS=49 but its own derivation (actions.py:358-364)
    # yields 109 — runtime inputs are clamped into the 49-wide embedding
    # (entity_encoder.py:72). We keep the true derived count here and mirror
    # the 49-wide embedding (with clamp) in the model config.
    assert A.NUM_QUEUE_ACTIONS == 109
    assert A.QUEUE_ACTION_EMBEDDING_DIM == 49
    assert A.NUM_BEGINNING_ORDER_ACTIONS == 174
    assert A.NUM_CUMULATIVE_STAT_ACTIONS == 167


def test_reorder_arrays():
    # every unit type maps back to its dense index
    for dense, game_id in enumerate(A.UNIT_TYPES[:20]):
        assert A.UNIT_TYPES_REORDER_ARRAY[game_id] == dense
    # ids outside the vocabulary are -1
    missing = [i for i in range(len(A.UNIT_TYPES_REORDER_ARRAY)) if i not in A.UNIT_TYPES]
    assert A.UNIT_TYPES_REORDER_ARRAY[missing[0]] == -1


def test_ability_remaps():
    assert A.UNIT_ABILITY_REORDER[0] == 0
    # spot check: every specific ability maps into the mix vocabulary
    for spec in A.UNIT_SPECIFIC_ABILITIES[:50]:
        idx = A.UNIT_ABILITY_REORDER[spec]
        assert 0 <= idx < A.NUM_UNIT_MIX_ABILITIES
        assert A.UNIT_MIX_ABILITIES[idx] == A.ABILITY_TO_GABILITY[spec]
    assert A.ABILITY_TO_QUEUE_ACTION[0] == 0
    assert A.ABILITY_TO_QUEUE_ACTION.max() == A.NUM_QUEUE_ACTIONS


def test_head_masks():
    assert A.SELECTED_UNITS_MASK.shape == (327,)
    # no_op selects nothing
    assert not A.SELECTED_UNITS_MASK[0]
    # Attack_unit (func_id 3) targets a unit
    attack_unit = A.FUNC_ID_TO_ACTION_TYPE[3]
    assert A.TARGET_UNIT_MASK[attack_unit]
    assert A.SELECTED_UNITS_MASK[attack_unit]
    assert not A.TARGET_LOCATION_MASK[attack_unit]


def test_queue_actions_are_train_or_research():
    for idx in A.QUEUE_ACTIONS:
        name = A.ACTIONS[idx]["name"]
        assert "Train_" in name or "Research" in name


def test_fake_step_data_schema():
    d = F.fake_step_data(train=True)
    assert set(d) == {
        "spatial_info", "scalar_info", "entity_info", "entity_num",
        "action_info", "action_mask", "selected_units_num",
    }
    assert d["spatial_info"]["height_map"].shape == F.SPATIAL_SIZE
    assert d["spatial_info"]["effect_PsiStorm"].shape == (F.EFFECT_LENGTH,)
    assert d["scalar_info"]["beginning_order"].shape == (20,)
    assert d["entity_info"]["unit_type"].shape == (F.MAX_ENTITY_NUM,)
    assert d["action_info"]["selected_units"].shape == (F.MAX_SELECTED_UNITS_NUM,)


def test_fake_model_output_schema():
    out = F.fake_model_output()
    assert out["logit"]["selected_units"].shape == (64, 513)
    assert out["logit"]["target_location"].shape == (152 * 160,)
    assert len(out["hidden_state"]) == 3
    teacher = F.fake_model_output(teacher=True)
    assert "action_info" not in teacher


def test_batch_tree():
    trees = [F.fake_step_data(train=False, rng=np.random.default_rng(i)) for i in range(3)]
    batched = F.batch_tree(trees)
    assert batched["spatial_info"]["height_map"].shape == (3, *F.SPATIAL_SIZE)
    assert batched["entity_num"].shape == (3,)
