"""Loss-function tests: shapes, masking semantics, gradient sanity."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distar_tpu.lib.features import MAX_SELECTED_UNITS_NUM
from distar_tpu.losses import (
    ReinforcementLossConfig,
    SupervisedLossConfig,
    compute_rl_loss,
    compute_sl_loss,
)

T, B, S, N = 4, 3, MAX_SELECTED_UNITS_NUM, 16
HEADS = ("action_type", "delay", "queued", "selected_units", "target_unit", "target_location")
SIZES = {"action_type": 327, "delay": 128, "queued": 2, "target_unit": N, "target_location": 80}


def _rl_inputs(rng, use_dapo=False):
    def logits(shape):
        return jnp.asarray(rng.standard_normal(shape).astype(np.float32))

    target_logit, teacher_logit, actions, blogp = {}, {}, {}, {}
    for h in HEADS:
        if h == "selected_units":
            target_logit[h] = logits((T, B, S, N + 1))
            teacher_logit[h] = logits((T, B, S, N + 1))
            actions[h] = jnp.asarray(rng.integers(0, N, (T, B, S)))
            blogp[h] = logits((T, B, S)) * 0.1
        else:
            n = SIZES[h]
            target_logit[h] = logits((T, B, n))
            teacher_logit[h] = logits((T, B, n))
            actions[h] = jnp.asarray(rng.integers(0, n, (T, B)))
            blogp[h] = logits((T, B)) * 0.1
    fields = ["winloss", "build_order", "built_unit", "effect", "upgrade", "battle"]
    values = {f: logits((T + 1, B)) for f in fields}
    rewards = {f: jnp.asarray(rng.integers(-1, 2, (T, B)).astype(np.float32)) for f in fields}
    sun = jnp.asarray(rng.integers(1, S, (T, B)))
    masks = {
        "actions_mask": {h: jnp.ones((T, B)) for h in HEADS},
        "selected_units_mask": jnp.arange(S)[None, None] < sun[..., None],
        "build_order_mask": jnp.ones((T, B)),
        "built_unit_mask": jnp.ones((T, B)),
        "effect_mask": jnp.ones((T, B)),
        "cum_action_mask": jnp.ones((T, B)),
    }
    inputs = {
        "target_logit": target_logit,
        "value": values,
        "action_log_prob": blogp,
        "teacher_logit": teacher_logit,
        "action": actions,
        "reward": rewards,
        "step": jnp.broadcast_to(jnp.arange(T)[:, None] * 100.0, (T, B)),
        "mask": masks,
        "entity_num": jnp.full((T, B), N - 2),
        "selected_units_num": sun,
    }
    if use_dapo:
        inputs["successive_logit"] = teacher_logit
    return inputs


def test_rl_loss_runs_and_is_finite(rng):
    inputs = _rl_inputs(rng)
    total, info = jax.jit(compute_rl_loss)(inputs)
    assert jnp.isfinite(total)
    for k, v in info.items():
        assert jnp.isfinite(v), k
    assert "pg/winloss/action_type" in info and "td/winloss" in info
    assert "kl/extra_at" in info


def test_rl_loss_only_update_value(rng):
    inputs = _rl_inputs(rng)
    cfg = ReinforcementLossConfig(only_update_value=True)
    total, info = compute_rl_loss(inputs, cfg)
    assert jnp.allclose(total, info["td/total"])


def test_rl_loss_teacher_equals_target_kl_zero(rng):
    inputs = _rl_inputs(rng)
    inputs["teacher_logit"] = inputs["target_logit"]
    _, info = compute_rl_loss(inputs)
    assert abs(float(info["kl/total"])) < 1e-4
    assert abs(float(info["kl/extra_at"])) < 1e-5


def test_rl_loss_gradients_flow(rng):
    inputs = _rl_inputs(rng)

    def loss_fn(target_logit):
        i = dict(inputs)
        i["target_logit"] = target_logit
        return compute_rl_loss(i)[0]

    g = jax.grad(loss_fn)(inputs["target_logit"])
    gn = sum(float(jnp.abs(v).sum()) for v in jax.tree.leaves(g))
    assert np.isfinite(gn) and gn > 0


def test_rl_loss_dapo(rng):
    inputs = _rl_inputs(rng, use_dapo=True)
    inputs["successive_logit"] = inputs["target_logit"]
    cfg = ReinforcementLossConfig(use_dapo=True, dapo_weight=0.1)
    total, info = compute_rl_loss(inputs, cfg)
    assert "dapo/total" in info
    # successive == target -> zero dapo
    assert abs(float(info["dapo/total"])) < 1e-4


def test_rl_loss_done_padding_semantics(rng):
    """A mid-window episode end (terminal step + pads): the bootstrap value
    and all padded-step values are ignored, and padded steps contribute no
    gradient — including the always-on action_type/delay heads."""
    inputs = _rl_inputs(rng)
    t_star = 1  # terminal step; steps t_star+1.. are pads
    step_mask = np.ones((T, B), np.float32)
    step_mask[t_star + 1:] = 0.0
    done = np.zeros((T, B), np.float32)
    done[t_star:] = 1.0
    inputs["mask"] = dict(inputs["mask"], step_mask=jnp.asarray(step_mask))
    inputs["done"] = jnp.asarray(done)
    # terminal reward at its real position, pads zeroed
    inputs["reward"] = {
        f: r * jnp.asarray(step_mask) for f, r in inputs["reward"].items()
    }

    total, _ = compute_rl_loss(inputs)

    # value estimates past the terminal step must not matter
    for rows in ([T], list(range(t_star + 1, T + 1))):
        poisoned = dict(inputs)
        poisoned["value"] = {
            f: v.at[jnp.asarray(rows)].set(1e3) for f, v in inputs["value"].items()
        }
        total_p, _ = compute_rl_loss(poisoned)
        assert jnp.allclose(total, total_p, atol=1e-5), rows

    # padded steps give zero gradient to every head's logits
    def loss_fn(target_logit):
        return compute_rl_loss(dict(inputs, target_logit=target_logit))[0]

    g = jax.grad(loss_fn)(inputs["target_logit"])
    for head, gh in g.items():
        pad_grad = float(jnp.abs(gh[t_star + 1:]).sum())
        assert pad_grad == 0.0, head
        assert float(jnp.abs(gh[: t_star + 1]).sum()) > 0.0, head


def _sl_inputs(rng):
    logits = {
        "action_type": jnp.asarray(rng.standard_normal((B, 327)).astype(np.float32)),
        "delay": jnp.asarray(rng.standard_normal((B, 128)).astype(np.float32)),
        "queued": jnp.asarray(rng.standard_normal((B, 2)).astype(np.float32)),
        "selected_units": jnp.asarray(rng.standard_normal((B, S, N + 1)).astype(np.float32)),
        "target_unit": jnp.asarray(rng.standard_normal((B, N)).astype(np.float32)),
        "target_location": jnp.asarray(rng.standard_normal((B, 80)).astype(np.float32)),
    }
    actions = {
        "action_type": jnp.asarray(rng.integers(0, 327, (B,))),
        "delay": jnp.asarray(rng.integers(0, 128, (B,))),
        "queued": jnp.asarray(rng.integers(0, 2, (B,))),
        "selected_units": jnp.asarray(rng.integers(0, N, (B, S))),
        "target_unit": jnp.asarray(rng.integers(0, N, (B,))),
        "target_location": jnp.asarray(rng.integers(0, 80, (B,))),
    }
    masks = {k: jnp.ones((B,)) for k in logits}
    sun = jnp.asarray(rng.integers(1, 8, (B,)))
    en = jnp.full((B,), N - 2)
    return logits, actions, masks, sun, en


def test_sl_loss_runs(rng):
    logits, actions, masks, sun, en = _sl_inputs(rng)
    total, info = jax.jit(compute_sl_loss)(logits, actions, masks, sun, en)
    assert jnp.isfinite(total)
    for k in ("action_type_loss", "selected_units_loss", "target_location_distance_L2",
              "selected_units_end_flag_loss", "action_type_acc"):
        assert k in info and jnp.isfinite(info[k]), k


def test_sl_loss_masked_head_contributes_zero(rng):
    logits, actions, masks, sun, en = _sl_inputs(rng)
    masks = dict(masks)
    masks["target_unit"] = jnp.zeros((B,))
    _, info = compute_sl_loss(logits, actions, masks, sun, en)
    assert float(info["target_unit_loss"]) == 0.0


def test_sl_loss_perfect_logits_low_loss(rng):
    logits, actions, masks, sun, en = _sl_inputs(rng)
    # make action_type logits nail the labels
    perfect = jax.nn.one_hot(actions["action_type"], 327) * 50.0
    logits = dict(logits, action_type=perfect)
    _, info = compute_sl_loss(logits, actions, masks, sun, en)
    assert float(info["action_type_loss"]) < 1e-3
    assert float(info["action_type_acc"]) == 1.0


def test_sl_loss_iou(rng):
    logits, actions, masks, sun, en = _sl_inputs(rng)
    # predictions exactly equal labels (with end token at position sun)
    preds = actions["selected_units"].copy()
    preds = preds.at[jnp.arange(B), jnp.clip(sun - 1, 0, S - 1)].set(en[0])
    _, info = compute_sl_loss(
        logits, actions, masks, sun, en, infer_selected_units=preds
    )
    assert 0.0 <= float(info["selected_units_iou"]) <= 1.0
