"""Shared-memory ring transport (comm/shm_ring.py): the zero-copy data
plane for colocated hops, its hello negotiation, and its typed failure
model (docs/data_plane.md transport-negotiation section)."""
import os
import socket
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from distar_tpu.comm import shm_ring
from distar_tpu.comm.serializer import recv_msg, send_msg
from distar_tpu.obs import get_registry
from distar_tpu.replay import (
    InsertClient,
    ReplayServer,
    ReplayStore,
    SampleClient,
    TableConfig,
)
from distar_tpu.replay.errors import BadHelloError


def _cfg(**kw):
    kw.setdefault("max_size", 128)
    kw.setdefault("sampler", "uniform")
    kw.setdefault("samples_per_insert", None)
    kw.setdefault("min_size_to_sample", 1)
    return TableConfig(**kw)


def _mint(capacity=1 << 16):
    server, fields = shm_ring.mint_ring_pair(capacity, op="test")
    client = shm_ring.attach_ring_pair(fields, op="test")
    return server, client, fields


# ------------------------------------------------------------- ring basics
def test_roundtrip_preserves_numpy_payloads():
    server, client, _ = _mint(1 << 20)
    try:
        payload = {"obs": np.arange(5000, dtype=np.float32),
                   "mask": np.ones((7, 3), dtype=bool), "n": 42}
        client.send(payload)
        got = server.recv(timeout_s=5.0)
        assert got["n"] == 42
        np.testing.assert_array_equal(got["obs"], payload["obs"])
        np.testing.assert_array_equal(got["mask"], payload["mask"])
        server.send({"code": 0})
        assert client.recv(timeout_s=5.0) == {"code": 0}
    finally:
        client.close()
        server.close()


def test_wraparound_many_frames_through_small_ring():
    """Hundreds of odd-sized frames through a 4 KiB ring: every frame
    crosses the wrap point eventually and every byte survives."""
    server, client, _ = _mint(4096)
    done = []

    def echo():
        for _ in range(300):
            server.send(server.recv(timeout_s=10.0))
        done.append(True)

    t = threading.Thread(target=echo, daemon=True)
    t.start()
    try:
        for i in range(300):
            blob = bytes([i % 256]) * ((i * 37) % 1800 + 1)
            assert client.request(blob, timeout_s=10.0) == blob
        t.join(10.0)
        assert done
    finally:
        client.close()
        server.close()


def test_frame_larger_than_ring_rejected_typed_at_send():
    server, client, _ = _mint(4096)
    try:
        with pytest.raises(shm_ring.ShmFrameTooLargeError):
            client.send(b"z" * 8192)
        # the ring is still usable: nothing of the oversized frame published
        client.send(b"ok")
        assert server.recv(timeout_s=5.0) == b"ok"
    finally:
        client.close()
        server.close()


def test_crc_corruption_detected_via_chaos_bitflip(chaos):
    """Bit-rot in the mapped segment (ChaosInjector.bitflip on the
    /dev/shm backing file) fails the frame CRC typed on read."""
    server, client, fields = _mint(4096)
    path = f"/dev/shm/{fields['shm_c2s']}"
    if not os.path.exists(path):  # non-Linux shm mount: nothing to flip
        pytest.skip("no /dev/shm backing file on this platform")
    try:
        client.send(b"a" * 3800)  # frame fills ~93% of the segment
        chaos.bitflip(path, flips=8)
        with pytest.raises(shm_ring.ShmError):
            server.recv(timeout_s=2.0)
    finally:
        client.close()
        server.close()


def test_doorbell_wake_latency_bounded():
    """A reader blocked on an empty ring wakes well inside the wait slice
    once the writer publishes (the UDP doorbell, not the 250 ms poll)."""
    server, client, _ = _mint()
    woke = {}

    def reader():
        server.recv(timeout_s=10.0)
        woke["t"] = time.monotonic()

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    try:
        time.sleep(0.3)  # reader is parked well past its initial checks
        t0 = time.monotonic()
        client.send(b"ding")
        t.join(5.0)
        assert "t" in woke
        assert woke["t"] - t0 < 0.2, "doorbell wake took a full poll slice"
    finally:
        client.close()
        server.close()


def test_ring_full_writer_blocks_then_resumes():
    server, client, _ = _mint(4096)
    try:
        client.send(b"x" * 3000)  # fills most of the ring
        result = {}

        def write_second():
            client.send(b"y" * 3000)  # cannot fit until the reader drains
            result["sent"] = True

        t = threading.Thread(target=write_second, daemon=True)
        t.start()
        time.sleep(0.15)
        assert "sent" not in result  # genuinely blocked on the full ring
        assert server.recv(timeout_s=5.0) == b"x" * 3000
        t.join(5.0)
        assert result.get("sent")
        assert server.recv(timeout_s=5.0) == b"y" * 3000
        wait = get_registry().snapshot().get(
            "distar_shm_ring_full_wait_seconds_count", 0.0)
        assert wait >= 1.0
    finally:
        client.close()
        server.close()


# -------------------------------------------------------------- liveness
def test_cross_process_roundtrip_and_writer_death_seen_from_reader():
    """A real subprocess attaches by name, echoes a frame, then dies
    WITHOUT closing (os._exit): the reader detects the dead writer typed
    within the heartbeat window."""
    server, fields = shm_ring.mint_ring_pair(1 << 20, op="xp")
    child = textwrap.dedent(f"""
        import os, sys, time
        sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
        from distar_tpu.comm import shm_ring
        peer = shm_ring.attach_ring_pair({fields!r}, op="xp")
        req = peer.recv(timeout_s=15)
        peer.send({{"echo": req}})
        time.sleep(0.2)
        os._exit(9)  # crash: no close, no atexit, beat thread dies with us
    """)
    proc = subprocess.Popen([sys.executable, "-c", child])
    try:
        server.send({"n": 7}, timeout_s=10.0)
        assert server.recv(timeout_s=10.0) == {"echo": {"n": 7}}
        proc.wait(timeout=15)
        t0 = time.monotonic()
        with pytest.raises(shm_ring.ShmPeerDeadError):
            server.recv(timeout_s=10.0)
        assert time.monotonic() - t0 < 2 * shm_ring.DEFAULT_HEARTBEAT_WINDOW_S
    finally:
        proc.kill()
        server.close()


def test_reader_death_seen_from_writer():
    """The opposite direction: the consuming side closes mid-stream and a
    writer blocked on the full ring surfaces it typed (not a timeout)."""
    server, client, _ = _mint(4096)
    try:
        server.close()  # reader of client's tx ring goes away
        with pytest.raises(shm_ring.ShmPeerDeadError):
            # needs to block for space -> sees the closed reader typed
            for _ in range(10):
                client.send(b"z" * 3000, timeout_s=5.0)
    finally:
        client.close()


# ------------------------------------------------------------ negotiation
def test_same_host_detection_false_on_spoofed_hostname():
    """A hello claiming a *different* host identity (spoofed hostname /
    wrong boot id) never gets rings, even when every other field checks
    out; the genuine identity does."""
    reply, peer = shm_ring.negotiate_server(
        {"transports": ["shm", "tcp"], "host": "spoofed-host|not-our-boot-id"},
        transport="auto")
    assert reply == {"transport": "tcp"} and peer is None

    reply, peer = shm_ring.negotiate_server(
        {"transports": ["shm", "tcp"], "host": shm_ring.host_identity()},
        transport="auto")
    try:
        assert reply["transport"] == "shm" and peer is not None
    finally:
        if peer is not None:
            peer.close()


def test_spoofed_host_over_live_server_stays_tcp():
    server = ReplayServer(ReplayStore(table_factory=lambda n: _cfg()),
                          port=0).start()
    try:
        with socket.create_connection((server.host, server.port), timeout=5) as s:
            send_msg(s, {"op": "hello", "compress": True,
                         "transports": ["shm", "tcp"],
                         "host": "evil-host|some-boot-id"}, compress=False)
            resp = recv_msg(s)
        assert resp["code"] == 0
        assert resp.get("transport") == "tcp"
        assert "shm_c2s" not in resp
    finally:
        server.stop()


def test_fallback_negotiation_when_shared_memory_unavailable(monkeypatch):
    """A host without multiprocessing.shared_memory (injected) negotiates
    tcp cleanly on both sides — no crash, no rings."""
    monkeypatch.setattr(shm_ring, "_sm", None)
    assert shm_ring.offer_transports("auto") == ["tcp"]
    reply, peer = shm_ring.negotiate_server(
        {"transports": ["shm", "tcp"], "host": shm_ring.host_identity()},
        transport="auto")
    assert reply == {"transport": "tcp"} and peer is None

    server = ReplayServer(ReplayStore(table_factory=lambda n: _cfg()),
                          port=0).start()
    try:
        client = InsertClient(server.host, server.port)
        client.insert("T", {"v": 1}, timeout_s=5.0)
        assert client.transport_active == "tcp"
        client.close()
    finally:
        server.stop()


def test_hostile_hello_garbage_transports_nacked_typed():
    """Satellite regression (mirrors the 18-EB header test): a hello whose
    transport names are ALL garbage must be NACK'd typed, never silently
    degraded to a working transport."""
    server = ReplayServer(ReplayStore(table_factory=lambda n: _cfg()),
                          port=0).start()
    try:
        with socket.create_connection((server.host, server.port), timeout=5) as s:
            send_msg(s, {"op": "hello", "compress": True,
                         "transports": ["carrier-pigeon", "smoke-signals"]},
                     compress=False)
            resp = recv_msg(s)
        assert resp["code"] == "bad_hello"
        assert "carrier-pigeon" in resp["error"]
    finally:
        server.stop()


def test_hostile_hello_garbage_codecs_nacked_typed():
    server = ReplayServer(ReplayStore(table_factory=lambda n: _cfg()),
                          port=0).start()
    try:
        with socket.create_connection((server.host, server.port), timeout=5) as s:
            send_msg(s, {"op": "hello", "compress": True,
                         "codecs": ["rot13", "base64"]}, compress=False)
            resp = recv_msg(s)
        assert resp["code"] == "bad_hello"
        # a recognized-but-unavailable codec still degrades (NOT a NACK)
        with socket.create_connection((server.host, server.port), timeout=5) as s:
            send_msg(s, {"op": "hello", "compress": True,
                         "codecs": ["zstd"]}, compress=False)
            resp = recv_msg(s)
        assert resp["code"] == 0
    finally:
        server.stop()


def test_serve_hello_garbage_transports_nacked_typed():
    """The serve plane NACKs the same way (one negotiation contract)."""
    from distar_tpu.serve import InferenceGateway, MockModelEngine, ServeTCPServer

    gw = InferenceGateway(MockModelEngine(2)).start()
    srv = ServeTCPServer(gw, port=0).start()
    try:
        with socket.create_connection((srv.host, srv.port), timeout=5) as s:
            send_msg(s, {"op": "hello", "transports": ["morse"]})
            resp = recv_msg(s)
        assert resp["code"] == "bad_hello"
    finally:
        srv.stop()
        gw.drain_and_stop(2.0)


def test_client_raises_typed_on_bad_hello():
    """A client whose own hello is NACK'd surfaces BadHelloError instead
    of silently degrading (config rot must be loud)."""
    server = ReplayServer(ReplayStore(table_factory=lambda n: _cfg()),
                          port=0).start()
    try:
        client = InsertClient(server.host, server.port)
        client._want_codecs = ["rot13"]  # simulate a corrupted preference
        with pytest.raises(BadHelloError):
            client.insert("T", {"v": 1}, timeout_s=5.0)
        client.close()
    finally:
        server.stop()


# ---------------------------------------------------------- replay e2e
def test_replay_insert_sample_over_shm_and_counters():
    server = ReplayServer(ReplayStore(table_factory=lambda n: _cfg()),
                          port=0).start()
    try:
        snap0 = get_registry().snapshot()
        ins = InsertClient(server.host, server.port)
        smp = SampleClient(server.host, server.port)
        item = {"x": np.arange(2048, dtype=np.float32)}
        ins.insert("T", item, timeout_s=5.0)
        assert ins.transport_active == "shm"
        items, info = smp.sample("T", batch_size=1, timeout_s=5.0)
        assert smp.transport_active == "shm"
        np.testing.assert_array_equal(items[0]["x"], item["x"])
        assert server.transport_counts()["shm"] == 2
        snap = get_registry().snapshot()
        assert snap.get("distar_shm_tx_frames_total", 0.0) > snap0.get(
            "distar_shm_tx_frames_total", 0.0)
        assert snap.get("distar_shm_rx_bytes_total", 0.0) > snap0.get(
            "distar_shm_rx_bytes_total", 0.0)
        ins.close()
        smp.close()
    finally:
        server.stop()


def test_ring_fault_falls_back_to_tcp_leg_with_zero_loss():
    """Kill ONLY the ring service mid-connection (ring fault, TCP leg
    alive): the client's next call completes over TCP on the SAME
    connection — typed, counted, nothing lost."""
    server = ReplayServer(ReplayStore(table_factory=lambda n: _cfg()),
                          port=0).start()
    try:
        ins = InsertClient(server.host, server.port)
        assert ins.insert("T", {"v": 0}, timeout_s=5.0) == 0
        assert ins.transport_active == "shm"
        before = sum(v for k, v in get_registry().snapshot().items()
                     if k.startswith("distar_shm_fallbacks_total"))
        for svc in list(server._ring_services):  # the injected ring fault
            svc.stop()
        assert ins.insert("T", {"v": 1}, timeout_s=5.0) == 1  # same call path
        assert ins.transport_active == "tcp"
        after = sum(v for k, v in get_registry().snapshot().items()
                    if k.startswith("distar_shm_fallbacks_total"))
        assert after == before + 1
        store_sizes = server.store.stats()["tables"]["T"]["size"]
        assert store_sizes == 2  # both inserts landed exactly once
        ins.close()
    finally:
        server.stop()


def test_subprocess_shard_roundtrip_over_shm():
    """End-to-end against a REAL shard subprocess (distinct PID): insert
    and sample both ride rings; the payload round-trips bit-exact."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "distar_tpu.replay.server", "--port", "0",
         "--min-size", "1", "--transport", "shm"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)
    try:
        parts = proc.stdout.readline().split()
        assert parts[0] == "REPLAY-SHARD", parts
        host, port = parts[1], int(parts[2])
        ins = InsertClient(host, port)
        smp = SampleClient(host, port)
        item = {"traj": np.random.default_rng(0).normal(size=4096).astype(np.float32)}
        ins.insert("T", item, timeout_s=10.0)
        assert ins.transport_active == "shm"
        items, _ = smp.sample("T", batch_size=1, timeout_s=10.0)
        np.testing.assert_array_equal(items[0]["traj"], item["traj"])
        ins.close()
        smp.close()
    finally:
        try:
            proc.stdin.close()
            proc.wait(timeout=10)
        except Exception:
            proc.kill()


# ------------------------------------------------------------- lifecycle
def test_rings_unlinked_on_close_and_on_crash_hook(tmp_path):
    """Leak check: segments vanish on clean close, and the resilience
    crash hook (FlightRecorder dump) unlinks whatever is still live."""
    server, client, fields = _mint()
    names = [fields["shm_c2s"], fields["shm_s2c"]]
    client.close()
    server.close()
    for name in names:
        with pytest.raises((FileNotFoundError, shm_ring.ShmError)):
            shm_ring.ShmRing.attach(name)

    # crash path: rings live when the process dies -> the flight-recorder
    # bundle dump runs shm_ring.unlink_all via add_crash_callback
    server2, fields2 = shm_ring.mint_ring_pair(1 << 16, op="crash")
    from distar_tpu.obs import get_flight_recorder

    get_flight_recorder().dump(str(tmp_path), reason="test-crash")
    for name in (fields2["shm_c2s"], fields2["shm_s2c"]):
        with pytest.raises((FileNotFoundError, shm_ring.ShmError)):
            shm_ring.ShmRing.attach(name)
    server2.close()  # idempotent on already-unlinked rings


def test_serve_client_over_shm_and_gateway_status():
    from distar_tpu.serve import InferenceGateway, MockModelEngine, ServeTCPServer
    from distar_tpu.serve.tcp_frontend import ServeClient

    gw = InferenceGateway(MockModelEngine(4, params={"version": "v1", "bias": 0.0}),
                          max_delay_s=0.002).start()
    gw.load_version("v1", params={"version": "v1", "bias": 0.0}, activate=True)
    srv = ServeTCPServer(gw, port=0).start()
    try:
        c = ServeClient(srv.host, srv.port)
        assert c.transport_active == "shm"
        out = c.act("s1", {"x": np.ones((4,), np.float32)})
        assert out
        results = c.act_many(
            [{"session_id": "s1", "obs": {"x": np.ones((4,), np.float32)}}])
        assert len(results) == 1 and not isinstance(results[0], Exception)
        assert gw.status()["transports"]["shm"] == 1
        tcp_client = ServeClient(srv.host, srv.port, transport="tcp")
        assert tcp_client.transport_active == "tcp"
        c.close()
        tcp_client.close()
    finally:
        srv.stop()
        gw.drain_and_stop(2.0)
