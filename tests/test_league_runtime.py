"""League runtime tier-1 tests (PR 20).

The matchmaking control plane must be deterministic enough to journal:
every assertion here pins a replay invariant the HA coordinator depends
on —

  * seeded branch distribution: ``ask_job`` draws branches from the
    configured per-class probabilities with the service RNG (statistical
    check + bit-exact sequence equality between same-seed services);
  * PFSP weights agree with the arena store's variance preview
    (``LeagueService.pfsp_weights`` == ``ArenaStore.pfsp_preview`` row) —
    matchmaking and the observatory must never disagree about who is
    worth playing;
  * snapshot minting is idempotent on (player, generation): a retried
    train-info can never mint the same checkpoint twice;
  * state_blob/load_state and route-by-route journal replay (the
    ``comm.ha.apply_record`` path) reconstruct an identical
    ``state_digest`` — roster, lineage, assignments, RNG cursor;
  * ``League.save_resume`` carries the runtime leg (satellite 6);
  * the elastic half: largest-remainder quotas, the payoff-driven
    reassigner's drain-before-grow ordering, publisher no-op on unknown
    players, and real actor-slot fleets spawning/draining under the PR 12
    supervisor;
  * the wire half: ``RemoteLeagueService`` round-trips every route
    through a real ``CoordinatorServer``.
"""
import os
import time

import pytest

from distar_tpu.arena import ArenaStore, set_arena_store
from distar_tpu.league.remote import RemoteLeagueService
from distar_tpu.league.runtime import (
    BRANCHES,
    LeagueService,
    PayoffReassigner,
    set_league_service,
)
from distar_tpu.league.runtime.reassign import _largest_remainder
from distar_tpu.league.runtime.runner import (
    LeaguePublisher,
    build_actor_fleets,
    league_cfg,
)
from distar_tpu.obs import MetricsRegistry, set_registry

ROSTER = ("MP0", "EP0", "ME0")


@pytest.fixture
def registry():
    reg = MetricsRegistry()
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


@pytest.fixture
def arena_global():
    """Process-global arena-store slot, restored on teardown."""
    prev = set_arena_store(None)
    yield
    set_arena_store(prev)


@pytest.fixture
def service_global():
    prev = None
    try:
        yield
    finally:
        set_league_service(prev)


def _service(seed: int = 7, lease_s: float = 5.0,
             job_ttl_s: float = 60.0) -> LeagueService:
    return LeagueService(league_cfg(ROSTER), seed=seed,
                         lease_s=lease_s, job_ttl_s=job_ttl_s)


# --------------------------------------------------------------- matchmaking
def test_branch_distribution_matches_configured_probs(registry, arena_global):
    """400 seeded asks per player land within 4 sigma of the configured
    branch probabilities (deterministic given the seed, so no flake), and
    a same-seed service reproduces the branch sequence bit-exactly."""
    n = 400
    expected = {
        "MP0": {"sp": 0.5, "pfsp": 0.5},
        "EP0": {"pfsp": 1.0},
        "ME0": {"vs_main": 0.3, "pfsp": 0.5, "eval": 0.2},
    }
    sequences = {}
    for pid, probs in expected.items():
        svc = _service(seed=11)
        counts = {b: 0 for b in BRANCHES}
        seq = []
        for i in range(n):
            job = svc.ask_job({"player_id": pid}, now=1000.0 + i)
            counts[job["branch"]] += 1
            seq.append((job["branch"], tuple(job["player_ids"])))
        sequences[pid] = seq
        for branch, p in probs.items():
            assert abs(counts[branch] / n - p) < 0.09, (pid, branch, counts)
        for branch in set(BRANCHES) - set(probs):
            assert counts[branch] == 0, (pid, branch, counts)

    # bit-exact determinism: same seed, same request stream, same draws
    for pid in expected:
        svc2 = _service(seed=11)
        replay = [
            (j["branch"], tuple(j["player_ids"]))
            for j in (svc2.ask_job({"player_id": pid}, now=1000.0 + i)
                      for i in range(n))
        ]
        assert replay == sequences[pid]


def test_ask_job_shapes_and_unknown_player(registry, arena_global):
    svc = _service()
    job = svc.ask_job({"player_id": "ME0", "learner_id": "L1"}, now=1.0)
    assert job["job_id"] == "J1"
    assert job["player_ids"][0] == "ME0"
    assert job["branch"] in BRANCHES
    if job["branch"] == "eval":
        assert job["send_data_players"] == []
    assert svc.ask_job({"player_id": "nope"}, now=2.0) is None
    status = svc.status(now=3.0)
    assert status["assignments_pending"] == 1
    assert status["assignments"]["J1"]["learner_id"] == "L1"


def test_pfsp_weights_agree_with_arena_preview(registry, arena_global):
    """The service's matchmaking weights ARE the arena's variance-PFSP
    row — same roster, same floats — and fall back to uniform when no
    store is hosted."""
    store = ArenaStore()
    set_arena_store(store)
    recs = []
    for i, (away, winner) in enumerate(
            [("MP0H1", "home")] * 6 + [("MP0H1", "away")] * 2
            + [("EP0H1", "home")] * 3 + [("EP0H1", "draw")] * 3
            + [("ME0H1", "away")] * 5):
        recs.append({"key": f"m{i}", "home": "MP0", "away": away, "round": 0,
                     "winner": winner, "game_steps": 10, "duration_s": 1.0})
    out = store.report_batch(recs)
    assert out["applied"] == len(recs)

    svc = _service()
    candidates = ["EP0H1", "ME0H1", "MP0H1"]
    weights = svc.pfsp_weights("MP0", candidates)
    row = store.pfsp_preview(["MP0"] + candidates)["MP0"]
    assert weights == [row[c] for c in candidates]
    assert sum(weights) > 0

    set_arena_store(None)
    assert svc.pfsp_weights("MP0", candidates) == pytest.approx([1 / 3] * 3)
    assert svc.pfsp_weights("MP0", []) == []


# ------------------------------------------------------------------- minting
def test_snapshot_minting_idempotent(registry, arena_global):
    svc = _service()
    svc.register_learner({"player_id": "MP0", "learner_id": "L1"}, now=1.0)
    hist0 = len(svc.league.historical_players)
    body = {"player_id": "MP0", "learner_id": "L1", "seq": 0,
            "train_steps": 5, "generation_path": "/ckpt/gen1.ckpt"}
    first = svc.train_info(dict(body), now=2.0)
    assert first["minted"] and first["snapshot_id"]
    minted_id = first["snapshot_id"]
    assert svc.league.historical_players[minted_id].checkpoint_path \
        == "/ckpt/gen1.ckpt"

    # retry with a fresh seq (ambiguous ack): same generation, no new mint
    again = svc.train_info({**body, "seq": 1}, now=3.0)
    assert not again["minted"] and again["snapshot_id"] == minted_id
    assert len(svc.league.historical_players) == hist0 + 1

    # duplicate seq: watermark absorbs the replay entirely
    dup = svc.train_info({**body, "seq": 1}, now=4.0)
    assert dup == {"ok": True, "duplicate": True}
    step = svc.league.active_players["MP0"].total_agent_step
    assert step == 10  # two applied train_infos, not three

    # a NEW generation mints a new player
    nxt = svc.train_info({**body, "seq": 2,
                          "generation_path": "/ckpt/gen2.ckpt"}, now=5.0)
    assert nxt["minted"] and nxt["snapshot_id"] != minted_id


def test_main_exploiter_reset_rolls_back_to_teacher(registry, arena_global):
    svc = LeagueService(league_cfg(ROSTER, teacher_path="/ckpt/teacher.ckpt"),
                        seed=3)
    svc.register_learner({"player_id": "ME0", "learner_id": "L1"}, now=1.0)
    out = svc.train_info({"player_id": "ME0", "learner_id": "L1", "seq": 0,
                          "generation_path": "/ckpt/me0g1.ckpt"}, now=2.0)
    assert out["minted"]
    # main exploiters always re-spawn from the teacher after a snapshot
    assert out["reset_checkpoint_path"] == "/ckpt/teacher.ckpt"
    assert svc.league.active_players["ME0"].checkpoint_path \
        == "/ckpt/teacher.ckpt"


# ------------------------------------------------- leases, freeze, expiry
def test_freeze_is_derived_and_thaws_on_reregister(registry, arena_global):
    svc = _service(lease_s=5.0)
    svc.register_learner({"player_id": "MP0", "learner_id": "L1"}, now=100.0)
    assert svc.status(now=101.0)["frozen_players"] == []
    # lease lapses: the player freezes without any stored tombstone
    st = svc.status(now=120.0)
    assert st["frozen_players"] == ["MP0"]
    assert st["active_learners"] == 0
    # a supervised restart re-registers (same learner id) and thaws
    reply = svc.register_learner({"player_id": "MP0", "learner_id": "L1"},
                                 now=121.0)
    assert reply["registered"] and reply["train_seq"] == -1
    assert svc.status(now=122.0)["frozen_players"] == []


def test_assignment_expiry_prunes_inside_journaled_routes(registry,
                                                          arena_global):
    svc = _service(job_ttl_s=60.0)
    svc.ask_job({"player_id": "MP0"}, now=100.0)
    assert svc.status(now=400.0)["assignments_pending"] == 1  # read-only
    svc.ask_job({"player_id": "EP0"}, now=400.0)  # journaled: prunes
    st = svc.status(now=401.0)
    assert st["assignments_pending"] == 1
    assert st["orphaned_jobs"] == 1
    # a report against the pruned job is not "completed" but still ingests
    out = svc.report({"job_id": "J1", "matches": []}, now=402.0)
    assert out["completed"] is False


def test_report_dedups_league_payoff_by_match_key(registry, arena_global):
    store = ArenaStore()
    set_arena_store(store)
    svc = _service()
    job = svc.ask_job({"player_id": "MP0"}, now=1.0)
    away = job["player_ids"][1]
    matches = [{"key": f"{job['job_id']}e0", "home": "MP0", "away": away,
                "round": 0, "winner": "home", "game_steps": 8,
                "duration_s": 1.0}]
    out = svc.report({"job_id": job["job_id"], "matches": matches}, now=2.0)
    assert out["completed"] and out["applied"] == 1
    games0 = svc.league.active_players["MP0"].total_game_count
    # replayed report (ambiguous ack): arena dedups, league view dedups
    out2 = svc.report({"job_id": job["job_id"], "matches": matches}, now=3.0)
    assert out2["duplicates"] == 1
    assert svc.league.active_players["MP0"].total_game_count == games0


# ----------------------------------------------------------------- durability
def _drive(svc: LeagueService, store: ArenaStore):
    """A scripted mutation sequence; returns the (route, body, ts) journal."""
    journal = []

    def call(route, method, body, ts):
        journal.append((route, body, ts))
        return getattr(svc, method)(body, now=ts)

    for i, pid in enumerate(ROSTER):
        call("league_register", "register_learner",
             {"player_id": pid, "learner_id": f"L{i}"}, 10.0 + i)
    for i in range(6):
        pid = ROSTER[i % 3]
        job = call("league_ask", "ask_job",
                   {"player_id": pid, "learner_id": f"L{i % 3}"}, 20.0 + i)
        matches = [{"key": f"{job['job_id']}e0", "home": pid,
                    "away": job["player_ids"][1], "round": 0,
                    "winner": ("home", "away", "draw")[i % 3],
                    "game_steps": 9, "duration_s": 0.5}]
        call("league_report", "report",
             {"job_id": job["job_id"], "learner_id": f"L{i % 3}",
              "matches": matches}, 30.0 + i)
    for i, pid in enumerate(ROSTER):
        call("league_train_info", "train_info",
             {"player_id": pid, "learner_id": f"L{i}", "seq": 0,
              "train_steps": 3,
              "generation_path": f"/ckpt/{pid}_g1.ckpt"}, 40.0 + i)
    return journal


def test_state_blob_and_journal_replay_reconstruct_digest(registry,
                                                          arena_global):
    """The two recovery paths the HA coordinator uses — snapshot install
    (state_blob/load_state) and WAL replay (apply_record with the record
    clock) — both land on a bit-identical structural digest."""
    from distar_tpu.comm.ha import apply_record

    store_a = ArenaStore()
    set_arena_store(store_a)
    svc_a = _service(seed=23)
    journal = _drive(svc_a, store_a)
    digest_a = svc_a.state_digest()
    assert digest_a["job_seq"] == 6
    assert len(digest_a["minted"]) == 3

    # snapshot path
    svc_b = _service(seed=99)  # wrong seed: load_state must overwrite RNG
    svc_b.load_state(svc_a.state_blob())
    assert svc_b.state_digest() == digest_a

    # WAL path: fresh service + fresh arena, replayed record by record
    store_c = ArenaStore()
    set_arena_store(store_c)
    svc_c = _service(seed=23)
    for route, body, ts in journal:
        apply_record(None, {"route": route, "body": body, "ts": ts},
                     league_service=svc_c)
    assert svc_c.state_digest() == digest_a
    # the forwarded reports landed in the replica's arena ledger too
    assert store_c.matches_total == store_a.matches_total


def test_league_save_resume_carries_runtime_state(registry, arena_global,
                                                  tmp_path):
    """Satellite 6: League.save_resume embeds the runtime leg, so a cold
    coordinator restore reconstructs roster + assignments + RNG cursor."""
    store = ArenaStore()
    set_arena_store(store)
    svc_a = _service(seed=5)
    _drive(svc_a, store)
    path = str(tmp_path / "league.resume")
    svc_a.league.save_resume(path)

    svc_b = _service(seed=77)
    svc_b.league.load_resume(path)
    assert svc_b.state_digest() == svc_a.state_digest()
    # the restored service keeps matchmaking from where A left off
    job_a = svc_a.ask_job({"player_id": "MP0"}, now=60.0)
    job_b = svc_b.ask_job({"player_id": "MP0"}, now=60.0)
    assert (job_a["branch"], job_a["player_ids"], job_a["job_id"]) \
        == (job_b["branch"], job_b["player_ids"], job_b["job_id"])


# ------------------------------------------------------------------ wire plane
def test_remote_league_service_roundtrip(registry, arena_global,
                                         service_global):
    """Every league route over a real CoordinatorServer, via the proxy the
    learners use (coordinator_request: retry fabric + HA failover)."""
    from distar_tpu.comm import Coordinator, CoordinatorServer

    store = ArenaStore()
    set_arena_store(store)
    svc = _service()
    set_league_service(svc)
    server = CoordinatorServer(coordinator=Coordinator(), port=0)
    server.start()
    try:
        remote = RemoteLeagueService(f"127.0.0.1:{server.port}")
        reply = remote.register_learner("MP0", learner_id="L1")
        assert reply["registered"] and reply["train_seq"] == -1
        job = remote.ask_job("MP0", learner_id="L1")
        assert job and job["job_id"] == "J1"
        out = remote.report(job["job_id"], [
            {"key": "J1e0", "home": "MP0", "away": job["player_ids"][1],
             "round": 0, "winner": "home", "game_steps": 4,
             "duration_s": 0.1}], learner_id="L1")
        assert out["completed"] and out["applied"] == 1
        info = remote.train_info("MP0", seq=0, train_steps=2,
                                 generation_path="/ckpt/g1.ckpt",
                                 learner_id="L1")
        assert info["minted"]
        status = remote.status()
        assert status["snapshot_mints"] == 1
        assert status["jobs_by_branch"][job["branch"]] == 1
        # GET mirror (opsctl league reads this)
        import json
        import urllib.request

        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/league/status",
                timeout=5) as resp:
            got = json.loads(resp.read())
        assert got["snapshot_mints"] == 1
    finally:
        server.stop()
        set_arena_store(None)


# --------------------------------------------------------------- elastic half
def test_largest_remainder_exact_and_deterministic():
    out = _largest_remainder({"a": 0.0, "b": 0.25, "c": 0.25}, 6, 1)
    assert sum(out.values()) == 6
    assert out == {"a": 1, "b": 3, "c": 2}  # tie broken by key order
    assert _largest_remainder({"a": 1.0, "b": 1.0}, 0, 0) == {"a": 0, "b": 0}
    # zero weights: even split of the spare
    assert _largest_remainder({"a": 0.0, "b": 0.0}, 4, 1) == {"a": 2, "b": 2}
    assert _largest_remainder({}, 5, 1) == {}
    # floors are granted before weights see anything
    out = _largest_remainder({"a": 100.0, "b": 0.0}, 3, 1)
    assert out["b"] >= 1 and sum(out.values()) == 3


class _FakeSupervisor:
    def __init__(self, fleets):
        self._fleets = dict(fleets)
        self.calls = []

    def fleets(self):
        return sorted(self._fleets)

    def actual(self, name):
        return self._fleets[name]

    def scale_up(self, name, n=1):
        self._fleets[name] += n
        self.calls.append(("up", name, n))

    def scale_down(self, name, n=1):
        self._fleets[name] -= n
        self.calls.append(("down", name, n))


class _FakeService:
    def __init__(self):
        self.moved = 0

    def note_reassignment(self, n=1):
        self.moved += n


def test_payoff_reassigner_moves_capacity_to_uncertain_pairs():
    """Solved pairs (winrate 1.0) starve; a 0.5 pair and an unplayed
    learner (exploration prior) gain — downscales run before upscales so
    the pool never exceeds its budget mid-move."""
    sup = _FakeSupervisor({"actors-MP0": 4, "actors-EP0": 1, "actors-ME0": 1})
    svc = _FakeService()
    cells = [
        {"a": "MP0", "b": "MP0H1", "games": 9, "win_rate": 1.0},
        {"a": "EP0", "b": "MP0H1", "games": 4, "win_rate": 0.5},
        # ME0 has no recorded pairs: gets the unplayed-variance prior
    ]
    r = PayoffReassigner(sup, {"actors-MP0": "MP0", "actors-EP0": "EP0",
                               "actors-ME0": "ME0"},
                         total_actors=6, min_actors=1,
                         payoff_fn=lambda: {"cells": cells}, service=svc)
    assert r.learning_weights() == {"actors-MP0": 0.0, "actors-EP0": 0.25,
                                    "actors-ME0": 0.25}
    deltas = r.step()
    assert deltas == {"actors-MP0": -3, "actors-EP0": 2, "actors-ME0": 1}
    assert sup._fleets == {"actors-MP0": 1, "actors-EP0": 3, "actors-ME0": 2}
    assert sup.calls[0][0] == "down"  # drain funds the grows
    assert svc.moved == 3
    # converged: a second pass is a no-op
    assert all(d == 0 for d in r.step().values())


def test_league_publisher_publishes_and_ignores_unknown_players():
    from types import SimpleNamespace

    from distar_tpu.serve.mux import GatewayMux
    from distar_tpu.serve.registry import ModelRegistry

    loaded = []

    def load_fn(source):
        loaded.append(source)
        return {"w": source}

    gw = SimpleNamespace(registry=ModelRegistry(load_fn=load_fn))
    pub = LeaguePublisher(GatewayMux({"MP0": gw}))
    assert pub.publish("MP0", "gen1", "/ckpt/g1.ckpt")
    gen, version, params = gw.registry.current()
    assert version == "gen1" and params == {"w": "/ckpt/g1.ckpt"}
    assert pub.published == {"MP0": "gen1"}
    # the league mints players faster than serving reconfigures: no-op
    assert pub.publish("EP0H1", "gen1", "/ckpt/x.ckpt") is False
    assert loaded == ["/ckpt/g1.ckpt"]


@pytest.mark.slow
def test_build_actor_fleets_spawns_and_drains(registry):
    """Real PR 12 fleets: one actor-slot fleet per player, ready-line
    handshake carries the player id, scale_down drains gracefully."""
    supervisor, fleet_players = build_actor_fleets(
        ("MP0", "EP0"), actors_per_player=2)
    try:
        assert fleet_players == {"actors-MP0": "MP0", "actors-EP0": "EP0"}
        assert supervisor.actual("actors-MP0") == 2
        member = supervisor.fleet("actors-MP0").members()[0]
        assert member.meta["player"] == "MP0"
        supervisor.scale_down("actors-EP0", 1)
        deadline = time.monotonic() + 10.0
        while supervisor.actual("actors-EP0") > 1 \
                and time.monotonic() < deadline:
            time.sleep(0.1)
        assert supervisor.actual("actors-EP0") == 1
    finally:
        supervisor.stop()


def test_self_play_opponent_resolves_live_state_each_window():
    """Live self-play (away == home) must re-read the learner state at every
    rollout window: the train step donates its state, so a stashed params
    reference is a deleted pytree after one optimizer step."""
    from types import SimpleNamespace

    from distar_tpu.league.runtime.runner import LeagueLearnerLoop

    learner = SimpleNamespace(_state={"params": {"w": 1}})
    loop = LeagueLearnerLoop("MP0", remote=None, learner=learner,
                             loader=None, learner_id="L1")
    job = {"job_id": "J1", "player_ids": ["MP0", "MP0"],
           "checkpoint_paths": ["", ""], "branch": "sp"}
    assert loop._resolve_opponent(job) == "MP0"
    assert loop.opponent_params() == {"w": 1}
    # simulate the donated train step swapping in a fresh state
    learner._state = {"params": {"w": 2}}
    assert loop.opponent_params() == {"w": 2}
