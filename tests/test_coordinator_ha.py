"""Coordinator HA: write-ahead journal recovery, warm-standby failover,
epoch fencing, ambiguous-ack typing, monotonic lease bookkeeping, and the
route-classification lint (distar_tpu/comm/ha.py; docs/resilience.md)."""
import os
import sys
import time

import pytest

from distar_tpu.comm import Coordinator, CoordinatorServer, coordinator_request
from distar_tpu.comm import coordinator as coordinator_mod
from distar_tpu.comm import discovery, ha
from distar_tpu.resilience import CommError

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")


@pytest.fixture(autouse=True)
def _fresh_targets():
    ha.reset_targets()
    yield
    ha.reset_targets()


# ------------------------------------------------------------- address parsing
def test_parse_addrs_forms():
    assert ha.parse_addrs("h1:1,h2:2") == (("h1", 1), ("h2", 2))
    assert ha.parse_addrs("h1:1") == (("h1", 1),)
    assert ha.parse_addrs(("h1", 9)) == (("h1", 9),)
    assert ha.parse_addrs([("a", 1), "b:2"]) == (("a", 1), ("b", 2))
    assert ha.parse_addrs(":7") == (("127.0.0.1", 7),)  # default host
    with pytest.raises(ValueError):
        ha.parse_addrs("")
    assert ha.format_addrs((("a", 1), ("b", 2))) == "a:1,b:2"


def test_discovery_norm_addr():
    assert discovery._norm_addr(("h", 5)) == ("h", 5)
    assert discovery._norm_addr("h:5") == ("h", 5)
    # HA comma specs come back with port=None — the request layer's marker
    assert discovery._norm_addr("a:1,b:2") == ("a:1,b:2", None)
    assert discovery._norm_addr(("a:1,b:2", None)) == ("a:1,b:2", None)


# ------------------------------------------------------------------ journaling
def test_journal_roundtrip_snapshot_and_compaction(tmp_path):
    root = str(tmp_path / "j")
    j = ha.Journal(root, snapshot_every=4)
    for i in range(3):
        j.append("register", {"token": "t", "ip": f"10.0.0.{i}", "port": i})
    j.snapshot({"state": {"marker": 3}})
    for i in range(3, 6):
        j.append("register", {"token": "t", "ip": f"10.0.0.{i}", "port": i})
    j.close()

    j2 = ha.Journal(root)
    base, records = j2.recover()
    assert base is not None and base["state"]["marker"] == 3
    # only the post-snapshot tail replays; seq continues where we left off
    assert [r["body"]["port"] for r in records] == [3, 4, 5]
    assert j2.seq == 6
    # compaction keeps at most the two newest snapshots
    j2.snapshot({"state": 1})
    j2.snapshot({"state": 2})
    j2.snapshot({"state": 3})
    snaps = [f for f in os.listdir(root) if f.startswith("snap.")]
    assert len(snaps) <= 2
    j2.close()


def test_journal_torn_tail_discarded(tmp_path):
    root = str(tmp_path / "j")
    j = ha.Journal(root)
    j.append("register", {"token": "t", "ip": "a", "port": 1})
    j.append("register", {"token": "t", "ip": "b", "port": 2})
    j.close()
    seg = sorted(p for p in os.listdir(root) if p.startswith("wal."))[0]
    path = os.path.join(root, seg)
    # tear the last record mid-payload: the crash-before-ack shape
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 3)
    base, records = ha.Journal(root).recover()
    assert base is None
    assert [r["body"]["ip"] for r in records] == ["a"]


def test_journal_corrupt_record_stops_scan(tmp_path):
    root = str(tmp_path / "j")
    j = ha.Journal(root)
    j.append("register", {"token": "t", "ip": "a", "port": 1})
    size_after_first = os.path.getsize(
        os.path.join(root, sorted(os.listdir(root))[0]))
    j.append("register", {"token": "t", "ip": "b", "port": 2})
    j.close()
    path = os.path.join(root, sorted(os.listdir(root))[0])
    # flip a payload bit inside the SECOND record: CRC mismatch stops the
    # scan there without touching the first record
    with open(path, "r+b") as f:
        f.seek(size_after_first + ha._FRAME.size + 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    _, records = ha.Journal(root).recover()
    assert [r["body"]["ip"] for r in records] == ["a"]


def test_journal_corrupt_snapshot_raises(tmp_path):
    root = str(tmp_path / "j")
    j = ha.Journal(root)
    j.append("register", {"token": "t", "ip": "a", "port": 1})
    j.snapshot({"state": 1})
    j.close()
    snap = [p for p in os.listdir(root) if p.startswith("snap.")][0]
    with open(os.path.join(root, snap), "r+b") as f:
        f.seek(8)
        f.write(b"\xff\xff")
    with pytest.raises(ha.JournalCorruptError):
        ha.Journal(root).recover()


# -------------------------------------------------- monotonic lease regression
class _WallJump:
    """time-module shim: wall clock jumped ``offset_s`` into the future,
    monotonic untouched — the NTP-step scenario lease sweeping must ignore."""

    def __init__(self, offset_s: float):
        self._offset = offset_s

    def time(self):
        return time.time() + self._offset

    def __getattr__(self, name):
        return getattr(time, name)


def test_lease_sweep_survives_wall_clock_jump(monkeypatch):
    co = Coordinator(default_lease_s=1000.0)
    co.register("svc", "10.0.0.1", 1)
    # a 2-hour NTP step forward: wall-clock-based leases would mass-evict
    monkeypatch.setattr(coordinator_mod, "time", _WallJump(7200.0))
    co._last_sweep = float("-inf")  # defeat the sweep rate limit
    assert [r["ip"] for r in co.peers("svc")] == ["10.0.0.1"]
    # eviction still works on MONOTONIC passage
    co._leases["10.0.0.1:1"] = time.monotonic() - 1.0
    co._last_sweep = float("-inf")
    assert co.peers("svc") == []


def test_replayed_lease_is_reaged_not_refreshed():
    co = Coordinator(default_lease_s=30.0)
    # replaying a record journaled 29s ago leaves ~1s of lease, not 30
    co.apply_register("svc", "10.0.0.1", 1, record_ts=time.time() - 29.0)
    remaining = co._leases["10.0.0.1:1"] - time.monotonic()
    assert 0.0 < remaining < 2.0
    # and one whose lease already lapsed during the outage is born expired
    co.apply_register("svc", "10.0.0.2", 2, record_ts=time.time() - 60.0)
    assert co._leases["10.0.0.2:2"] < time.monotonic()
    co._last_sweep = float("-inf")
    assert {r["ip"] for r in co.peers("svc")} == {"10.0.0.1"}


# ------------------------------------------------------- ambiguous-ack typing
def test_is_ambiguous_classification():
    # a refused/unresolvable connection never carried the request
    assert not ha.is_ambiguous(ConnectionRefusedError())
    assert not ha.is_ambiguous(
        CommError("x", cause=ConnectionRefusedError()))
    err = CommError("x")
    err.__cause__ = ConnectionRefusedError()
    assert not ha.is_ambiguous(err)
    # timeouts / resets / truncated replies may have been applied
    assert ha.is_ambiguous(TimeoutError())
    assert ha.is_ambiguous(CommError("x", cause=TimeoutError()))
    assert ha.is_ambiguous(CommError("x"))


def test_failover_idempotent_retried_once_nonidempotent_typed(monkeypatch):
    calls = []

    def flaky_once(host, port, route, body, timeout):
        calls.append((host, port))
        if len(calls) == 1:
            # mid-flight death: ambiguous (not a refused connection)
            raise CommError("reset", cause=TimeoutError())
        return {"code": 0, "info": True, "epoch": 1}

    monkeypatch.setattr(coordinator_mod, "_coordinator_request_once",
                        flaky_once)
    # idempotent route: the ambiguous failure rotates and is retried —
    # exactly one extra attempt lands on the standby
    r = coordinator_request("a:1,b:2", None, "register",
                            {"token": "t", "ip": "x", "port": 1})
    assert r["code"] == 0
    assert calls == [("a", 1), ("b", 2)]

    # non-idempotent `ask`: the same failure surfaces typed instead of
    # retrying into a possible double-pop; no second attempt is made
    calls.clear()
    ha.reset_targets()
    with pytest.raises(ha.AmbiguousAckError) as ei:
        coordinator_request("a:1,b:2", None, "ask", {"token": "t"})
    assert len(calls) == 1
    assert ei.value.route == "ask"


def test_failover_refused_connection_is_not_ambiguous(monkeypatch):
    calls = []

    def down_then_up(host, port, route, body, timeout):
        calls.append((host, port))
        if host == "a":
            raise CommError("refused", cause=ConnectionRefusedError())
        return {"code": 0, "info": None, "epoch": 1}

    monkeypatch.setattr(coordinator_mod, "_coordinator_request_once",
                        down_then_up)
    # `ask` against a DEAD primary is safe to retry: the request never
    # left this process, so the pop cannot have been applied
    r = coordinator_request("a:1,b:2", None, "ask", {"token": "t"})
    assert r["code"] == 0 and calls == [("a", 1), ("b", 2)]


# --------------------------------------------------------------- epoch fencing
def test_stale_epoch_reply_is_fenced(monkeypatch):
    targets = ha.targets_for(ha.parse_addrs("a:1,b:2"))
    targets.note_epoch(5)

    def deposed(host, port, route, body, timeout):
        return {"code": 0, "info": [], "epoch": 3}

    monkeypatch.setattr(coordinator_mod, "_coordinator_request_once", deposed)
    with pytest.raises(ha.StaleEpochError):
        coordinator_mod._failover_request_once(targets, "peers", {}, 5.0)
    # the deposed answerer was rotated away from
    assert targets.active() == ("b", 2)


def test_not_leader_redirect_follows_hint(monkeypatch):
    targets = ha.targets_for(ha.parse_addrs("a:1,b:2"))

    def standby(host, port, route, body, timeout):
        return {"code": 2, "info": "not_leader", "leader": "b:2", "epoch": 4}

    monkeypatch.setattr(coordinator_mod, "_coordinator_request_once", standby)
    with pytest.raises(ha.NotLeaderError):
        coordinator_mod._failover_request_once(targets, "peers", {}, 5.0)
    assert targets.active() == ("b", 2)
    assert targets.max_epoch == 4


def test_failover_notifies_listeners():
    targets = ha.targets_for(ha.parse_addrs("a:1,b:2"))
    hits = []
    listener = hits.append
    ha.add_failover_listener(listener)
    try:
        targets.rotate(("a", 1))
    finally:
        ha.remove_failover_listener(listener)
    assert hits and hits[0] is targets


# ----------------------------------------------------------- route-set lint
def test_lint_ha_routes_clean():
    sys.path.insert(0, TOOLS)
    try:
        import lint_ha_routes

        assert lint_ha_routes.lint() == []
    finally:
        sys.path.remove(TOOLS)


def test_route_sets_invariants():
    assert not (ha.JOURNALED_ROUTES & ha.EPHEMERAL_ROUTES)
    assert ha.DURABLE_ROUTES <= ha.JOURNALED_ROUTES
    assert "ask" not in ha.IDEMPOTENT_ROUTES


# ------------------------------------------------------------ shipper resync
def test_shipper_resync_counted():
    from distar_tpu.obs import (
        MetricsRegistry, TelemetryIngest, TelemetryShipper, TimeSeriesStore,
    )
    from distar_tpu.obs import shipper as shipper_mod

    reg = MetricsRegistry()
    reg.counter("x_total", "seed one counter so snapshots are non-empty").inc()
    ingest = TelemetryIngest(TimeSeriesStore())
    s = TelemetryShipper("t-ha", ingest=ingest, interval_s=60.0, registry=reg)
    s.start()
    try:
        assert shipper_mod.request_resync_all("heartbeat") >= 1
        c = reg.counter("distar_obs_shipper_resyncs_total",
                        "full-snapshot re-ships after broker restart "
                        "or failover", reason="heartbeat")
        deadline = time.time() + 5.0
        while c.value < 1 and time.time() < deadline:
            time.sleep(0.05)
        assert c.value >= 1, "resync never shipped/counted"
    finally:
        s.stop()


# ------------------------------------------------- end-to-end warm standby
def _spawn(role, port, journal_dir, peers=(), grace=0.8):
    co = Coordinator(default_lease_s=5.0)
    srv = CoordinatorServer(coordinator=co, port=port)
    state = ha.HAState(co, journal_dir,
                       advertise=f"127.0.0.1:{srv.port}",
                       role=role, peers=list(peers),
                       takeover_grace_s=grace,
                       arena_store_fn=lambda: None)
    state.boot()
    srv.attach_ha(state)
    srv.start()
    return co, srv, state


def test_ha_pair_failover_end_to_end(tmp_path):
    co1, srv1, ha1 = _spawn("primary", 0, str(tmp_path / "j1"))
    addr1 = f"127.0.0.1:{srv1.port}"
    co2, srv2, ha2 = _spawn("standby", 0, str(tmp_path / "j2"),
                            peers=[addr1])
    addr2 = f"127.0.0.1:{srv2.port}"
    spec = f"{addr1},{addr2}"
    try:
        time.sleep(0.3)
        # replies are epoch/role-stamped; the standby replicates before ack
        r = coordinator_request(spec, None, "register",
                                {"token": "t", "ip": "10.0.0.1", "port": 1})
        assert r["code"] == 0 and r["role"] == "primary"
        assert int(r["epoch"]) >= 1
        deadline = time.time() + 2.0
        while not co2.peers("t") and time.time() < deadline:
            time.sleep(0.05)
        assert co2.peers("t"), "standby did not replicate the register"

        # a standby addressed directly answers the typed not_leader envelope
        host, port = addr2.split(":")
        reply = coordinator_mod._coordinator_request_once(
            host, int(port), "register",
            {"token": "x", "ip": "z", "port": 9}, 5.0)
        assert reply["code"] == 2 and reply["info"] == "not_leader"
        assert reply["leader"] == addr1

        # pop on the primary; the pop itself replicates (no resurrection)
        got = coordinator_request(spec, None, "ask", {"token": "t"})
        assert got["info"]["ip"] == "10.0.0.1"
        coordinator_request(spec, None, "register",
                            {"token": "t", "ip": "10.0.0.2", "port": 2})

        # SIGKILL-equivalent: stop the primary without a parting snapshot
        epoch_before = ha2.epoch
        srv1.stop()
        ha1._stop.set()
        deadline = time.time() + 10.0
        while ha2.role != "primary" and time.time() < deadline:
            time.sleep(0.05)
        assert ha2.role == "primary", "standby never promoted"
        assert ha2.epoch > epoch_before

        # the comma-spec client follows leadership without code changes
        r = coordinator_request(spec, None, "peers", {"token": "t"})
        assert r["role"] == "primary"
        assert [p["ip"] for p in r["info"]] == ["10.0.0.2"]
    finally:
        for srv, st in ((srv1, ha1), (srv2, ha2)):
            try:
                srv.stop()
            except Exception:
                pass
            st.stop()


def test_cold_restart_replays_journal_exactly(tmp_path):
    root = str(tmp_path / "j")
    co1, srv1, ha1 = _spawn("primary", 0, root)
    spec = f"127.0.0.1:{srv1.port}"
    try:
        host, port = spec.split(":")
        for i in range(4):
            coordinator_request(host, int(port), "register",
                                {"token": "q", "ip": f"10.1.0.{i}", "port": i})
        got = coordinator_request(host, int(port), "ask", {"token": "q"})
        assert got["info"]["ip"] == "10.1.0.0"
    finally:
        srv1.stop()
        ha1._stop.set()  # crash-stop: no final snapshot

    co2 = Coordinator(default_lease_s=5.0)
    ha2 = ha.HAState(co2, root, advertise="127.0.0.1:1", role="primary",
                     arena_store_fn=lambda: None)
    ha2.boot()
    try:
        ips = [r["ip"] for r in co2.peers("q")]
        assert ips == ["10.1.0.1", "10.1.0.2", "10.1.0.3"], \
            "replay must reconstruct the queue minus the acked pop"
        # the restarted primary leads a NEW epoch (fencing the old one out)
        assert ha2.epoch > ha1.epoch - 1
    finally:
        ha2.stop()
