"""Data-plane tests: serializer, C++ shuttle (with Python-fallback parity),
coordinator brokering, adapter push/pull end to end."""
import threading
import time

import numpy as np
import pytest

from distar_tpu.comm import (
    Adapter,
    Coordinator,
    CoordinatorServer,
    coordinator_request,
    dumps,
    loads,
    shuttle,
)


def test_serializer_roundtrip():
    obj = {"a": np.arange(1000, dtype=np.float32).reshape(10, 100), "b": [1, "x"], "c": None}
    for compress in (True, False):
        out = loads(dumps(obj, compress=compress))
        np.testing.assert_array_equal(out["a"], obj["a"])
        assert out["b"] == obj["b"] and out["c"] is None


def test_native_shuttle_builds():
    assert shuttle.native_available(), "C++ shuttle failed to build"


def _roundtrip(serve_fn, fetch_fn, payload):
    port = serve_fn(payload, 1, 10_000)
    return fetch_fn("127.0.0.1", port, 10_000)


def test_shuttle_native_roundtrip():
    payload = bytes(np.random.default_rng(0).integers(0, 256, 5_000_000, dtype=np.uint8))
    got = _roundtrip(shuttle.serve, shuttle.fetch, payload)
    assert got == payload


def test_shuttle_cross_impl_parity():
    """Python client must read what the C++ server wrote, and vice versa."""
    payload = b"x" * 100_000
    port = shuttle.serve(payload, 1, 10_000)  # native (when built)
    assert shuttle._py_fetch("127.0.0.1", port, 10_000) == payload
    port = shuttle._py_serve(payload, 1, 10_000)
    assert shuttle.fetch("127.0.0.1", port, 10_000) == payload


def test_shuttle_multi_accept():
    payload = b"model-weights" * 1000
    port = shuttle.serve(payload, 3, 10_000)
    for _ in range(3):
        assert shuttle.fetch("127.0.0.1", port, 10_000) == payload


def test_coordinator_broker():
    co = Coordinator()
    assert co.ask("traj") is None
    assert co.depth("traj") == 0
    co.register("traj", "1.2.3.4", 1111, {"n": 1})
    co.register("traj", "1.2.3.4", 2222)
    assert co.depth("traj") == 2  # broker backlog (soak staleness accounting)
    assert co.depth("traj", max_age_s=3600) == 2  # fresh records count
    assert co.depth("traj", max_age_s=0) == 0  # expired serve windows don't
    rec = co.ask("traj")
    assert (rec["ip"], rec["port"]) == ("1.2.3.4", 1111)  # FIFO
    assert co.depth("traj") == 1
    # strikes purge dead endpoints
    for _ in range(5):
        co.strike("1.2.3.4", 2222)
    assert co.ask("traj") is None


def test_coordinator_http():
    srv = CoordinatorServer()
    srv.start()
    try:
        coordinator_request(srv.host, srv.port, "register", {"token": "t", "ip": "a", "port": 1})
        rec = coordinator_request(srv.host, srv.port, "ask", {"token": "t"})["info"]
        assert rec["port"] == 1
        assert coordinator_request(srv.host, srv.port, "ask", {"token": "t"})["info"] is None
    finally:
        srv.stop()


def test_adapter_push_pull_inprocess():
    co = Coordinator()
    producer = Adapter(coordinator=co)
    consumer = Adapter(coordinator=co)
    traj = {"obs": np.ones((16, 4), np.float32), "reward": np.zeros(16)}
    producer.push("MP0traj", traj)
    out = consumer.pull("MP0traj", timeout=10)
    np.testing.assert_array_equal(out["obs"], traj["obs"])


def test_adapter_pull_loop_and_backpressure():
    co = Coordinator()
    producer = Adapter(coordinator=co)
    consumer = Adapter(coordinator=co)
    cache = consumer.start_pull_loop("tok", maxlen=2)
    for i in range(4):
        producer.push("tok", {"i": i}, timeout_ms=5_000)
    deadline = time.time() + 10
    while len(cache) < 2 and time.time() < deadline:
        time.sleep(0.05)
    assert len(cache) == 2  # bounded by maxlen
    got = [cache.popleft()["i"], cache.popleft()["i"]]
    assert got == [0, 1]
    consumer.stop()


def test_adapter_via_http_coordinator():
    srv = CoordinatorServer()
    srv.start()
    try:
        producer = Adapter(coordinator_addr=(srv.host, srv.port))
        consumer = Adapter(coordinator_addr=(srv.host, srv.port))
        producer.push("w", {"step": 7})
        assert consumer.pull("w", timeout=10)["step"] == 7
    finally:
        srv.stop()
