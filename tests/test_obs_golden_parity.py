"""Obs-transform golden parity vs the reference Features.

tools/record_reference_obs_golden.py runs the REFERENCE
``Features.transform_obs`` + ``reverse_raw_action`` (reference
features.py:463,854) on the shared dummy protos from
``dummy_obs.build_parity_fixtures`` and records every output field; here the
SAME fixtures run through ``envs/features.ProtoFeatures`` and each field
must agree — the field-level cross-check of the whole obs contract (spatial
planes, effect lists, the 38-field entity rows and their LUT remaps, scalar
stats, value features, replay action decoding and born locations).

Documented structural divergences (TPU-first re-architecture, not drift):
  * our entity arrays leave transform_obs padded to MAX_ENTITY_NUM (static
    shapes) — compared on the first entity_num rows;
  * our ``last_*`` entity/scalar fields and Z-conditioning scalars are
    zero-initialised here (the agent/decoder fills them) — the reference
    omits them entirely at this layer;
  * our value_feature carries the extra Z keys the value encoder consumes
    and stores own/enemy spatial masks without the leading singleton axis;
  * our masks are spec-driven; the reference's are presence-driven. They
    agree on every decodable action, which is what the SL loss sees (the
    decoder drops invalid steps on both sides).

Fixtures are generated on demand (the reference + torch live in this
image); skipped cleanly where /root/reference is absent.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

from distar_tpu.envs.dummy_obs import build_parity_fixtures
from distar_tpu.envs.features import ProtoFeatures

REF = "/root/reference"
GOLDEN_DIR = os.environ.get("GOLDEN_DIR", "/tmp/golden_ref")
RECORDER = os.path.join(
    os.path.dirname(__file__), "..", "tools", "record_reference_obs_golden.py"
)

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference repo not available"
)


@pytest.fixture(scope="module")
def golden():
    sys.path.insert(0, os.path.dirname(RECORDER))
    from record_reference_obs_golden import fixture_fingerprint

    path = os.path.join(GOLDEN_DIR, "obs_transform.npz")
    want = fixture_fingerprint()
    stale = True
    if os.path.exists(path):
        z = np.load(path, allow_pickle=True)
        stale = (
            "meta/fingerprint" not in z.files
            or str(z["meta/fingerprint"]) != want
        )
    if stale:  # cache recorded from OLDER fixtures (or absent): re-record
        subprocess.run(
            [sys.executable, RECORDER, "--out", GOLDEN_DIR],
            check=True,
            timeout=1800,
            cwd="/tmp",
        )
    return np.load(path, allow_pickle=True)


@pytest.fixture(scope="module")
def ours():
    fx = build_parity_fixtures()
    pf = ProtoFeatures(fx["game_info"])
    ret = pf.transform_obs(
        fx["obs"], padding_spatial=True, opponent_obs=fx["opponent_obs"]
    )
    return fx, pf, ret


def _close(ref, got, key):
    ref = np.asarray(ref)
    got = np.asarray(got)
    assert ref.shape == got.shape, f"{key}: shape {got.shape} != ref {ref.shape}"
    if ref.dtype.kind == "f" or got.dtype.kind == "f":
        np.testing.assert_allclose(
            got.astype(np.float32), ref.astype(np.float32),
            rtol=2e-3, atol=2e-3, err_msg=key,
        )
    else:
        np.testing.assert_array_equal(
            got.astype(np.int64), ref.astype(np.int64), err_msg=key
        )


def test_spatial_planes(golden, ours):
    _, _, ret = ours
    keys = [k for k in golden.files if k.startswith("spatial/")]
    assert len(keys) == 13  # 7 minimap planes + 6 effect coordinate lists
    for k in keys:
        _close(golden[k], ret["spatial_info"][k.split("/", 1)[1]], k)


def test_entity_fields(golden, ours):
    _, _, ret = ours
    n = int(golden["entity_num"])
    assert int(ret["entity_num"]) == n
    keys = [k for k in golden.files if k.startswith("entity/")]
    assert len(keys) == 34  # every field the reference emits
    for k in keys:
        name = k.split("/", 1)[1]
        _close(golden[k], ret["entity_info"][name][:n], k)


def test_scalar_fields(golden, ours):
    _, _, ret = ours
    keys = [k for k in golden.files if k.startswith("scalar/")]
    assert len(keys) == 9
    for k in keys:
        _close(golden[k], ret["scalar_info"][k.split("/", 1)[1]], k)


def test_game_info(golden, ours):
    _, _, ret = ours
    gi = ret["game_info"]
    assert gi["map_name"] == str(golden["game/map_name"])
    assert gi["game_loop"] == int(golden["game/game_loop"])
    np.testing.assert_array_equal(np.asarray(gi["tags"]), golden["game/tags"])
    np.testing.assert_array_equal(
        np.asarray(ret["action_result"]), golden["game/action_result"]
    )
    assert ret["battle_score"] == pytest.approx(float(golden["game/battle_score"]))
    assert ret["opponent_battle_score"] == pytest.approx(
        float(golden["game/opponent_battle_score"])
    )


def test_born_locations(golden, ours):
    fx, pf, _ = ours
    home, away = pf.born_locations(fx["first_obs"])
    assert home == int(golden["meta/home_born_location"])
    assert away == int(golden["meta/away_born_location"])


def test_value_feature(golden, ours):
    _, _, ret = ours
    vf = ret["value_feature"]
    keys = [k for k in golden.files if k.startswith("vf/")]
    assert len(keys) == 11
    for k in keys:
        name = k.split("/", 1)[1]
        ref = golden[k]
        if name in ("own_units_spatial", "enemy_units_spatial"):
            ref = np.squeeze(ref, axis=0)  # ours drops the singleton channel
        _close(ref, vf[name], k)


def test_z_extraction_parity(golden, ours):
    """extract_z vs the reference get_z on the shared decoded-action stream:
    zergling-spam cap, spine proximity filter, cumulative marking, 20-slot
    truncation (reference features.py:419-460)."""
    from distar_tpu.envs.features import extract_z

    fx, pf, _ = ours
    home, away = pf.born_locations(fx["first_obs"])
    assert home == int(golden["meta/home_born_location"])
    bo, cum, bo_len, bo_loc = extract_z(fx["z_stream"], home, away)
    np.testing.assert_array_equal(bo, golden["z/beginning_order"])
    np.testing.assert_array_equal(cum, golden["z/cumulative_stat"])
    assert bo_len == int(golden["z/bo_len"])
    np.testing.assert_array_equal(bo_loc, golden["z/bo_location"])


def test_reverse_raw_action_parity(golden, ours):
    fx, pf, ret = ours
    tags = ret["game_info"]["tags"]
    names = sorted({k.split("/")[1] for k in golden.files if k.startswith("act/")})
    assert len(names) == len(fx["actions"]) == 9
    for name, raw_action in fx["actions"]:
        g = {
            k.split("/", 2)[2]: golden[k]
            for k in golden.files
            if k.startswith(f"act/{name}/")
        }
        rev = pf.reverse_raw_action(raw_action, tags)
        assert rev["invalid"] == bool(g["invalid"]), name
        if rev["invalid"]:
            continue  # both sides discard these steps in the decoder
        act = rev["action"]
        for field in ("action_type", "queued", "target_unit", "target_location"):
            assert int(act[field]) == int(g[field]), f"{name}/{field}"
        sun = int(rev["selected_units_num"])
        assert sun == int(g["selected_units_num"]), name
        np.testing.assert_array_equal(
            act["selected_units"][:sun], g["selected_units"], err_msg=name
        )
        for field in ("action_type", "queued", "selected_units", "target_unit",
                      "target_location"):
            assert bool(rev["mask"][field]) == bool(g[f"mask_{field}"]), (
                f"{name}/mask_{field}"
            )
        # last-action augmentation inputs for the decoder
        np.testing.assert_array_equal(
            np.asarray(rev["selected_tags"], np.int64),
            g["last_selected_tags"],
            err_msg=name,
        )
        ref_target = int(g["last_target_tag"])
        got_target = -1 if rev["target_tag"] is None else int(rev["target_tag"])
        assert got_target == ref_target, name
