"""End-to-end replay-store acceptance: a toy fleet trains through the store
with samples-per-insert enforced, the store is killed and restarted mid-run,
and every acked insert is recovered from spill — plus the counter-demo
showing the loss the spill/retry fabric prevents.

The fleet is real plumbing with toy payloads: the real ``Actor`` push path
(config-switched replay target -> ``InsertClient`` with retry/breaker) on
the real adapter/coordinator stack, the real ``ReplayServer``/``SpillRing``,
and the learner side is the real ``ReplayDataLoader`` feeding
``collate_trajectories`` — only the trajectories are schema-minimal
(full-model training through collate is tests/test_pipeline.py)."""
import threading
import time

import pytest

from distar_tpu.learner.rl_dataloader import ReplayDataLoader
from distar_tpu.replay import (
    InsertClient,
    ReplayServer,
    ReplayStore,
    SampleClient,
    SpillRing,
    TableConfig,
)
from distar_tpu.resilience import ChaosInjector, NO_RETRY

from test_rl_dataloader import tiny_traj

PLAYER = "MP0"
BATCH = 2
SPI = 2.0
MIN_SIZE = 4


def _table_cfg(spi=SPI):
    return TableConfig(max_size=256, sampler="uniform", samples_per_insert=spi,
                       min_size_to_sample=MIN_SIZE, error_buffer=SPI)


def _traj(uid: float):
    traj = tiny_traj()
    traj[0]["model_last_iter"] = float(uid)  # collated per-trajectory: the id
    return traj


def _make_actor(addr: str):
    """A real Actor on the real in-process coordinator/adapter stack, with
    the replay push target config-switched ON."""
    from distar_tpu.actor import Actor
    from distar_tpu.comm import Adapter, Coordinator

    return Actor(
        cfg={"actor": {"replay": {"enabled": True, "addr": addr}}},
        adapter=Adapter(coordinator=Coordinator()),
    )


class _Producer(threading.Thread):
    """Toy actor thread: pushes uid-tagged trajectories through the real
    Actor replay path until stopped; acked uids are exactly the Actor's
    successful inserts (failures are dropped + counted, like production)."""

    def __init__(self, actor, start_uid: int):
        super().__init__(daemon=True)
        self._actor = actor
        self._uid = start_uid
        self._halt = threading.Event()  # NOT _stop: Thread.join uses _stop()
        self.acked = []

    def run(self):
        while not self._halt.is_set():
            uid = self._uid
            before = _pushed_count(self._actor)
            self._actor.push_trajectory(PLAYER, _traj(uid))
            if _pushed_count(self._actor) > before:  # acked, not dropped
                self.acked.append(float(uid))
                self._uid += 1

    def stop(self):
        self._halt.set()


def _pushed_count(actor) -> float:
    from distar_tpu.obs import get_registry

    return get_registry().counter(
        "distar_actor_replay_pushed_total",
        "trajectories acked by the replay store", player=PLAYER,
    ).value


def _drain(loader, batches: int, sampled_uids: set, timeout_s: float = 60.0):
    """The toy learner: consume ``batches`` collated batches, recording the
    per-trajectory uids (batch["model_last_iter"]) it trained on."""
    deadline = time.monotonic() + timeout_s
    done = 0
    while done < batches:
        assert time.monotonic() < deadline, "learner starved past its budget"
        batch = next(loader)
        assert batch["reward"].shape[1] == BATCH
        sampled_uids.update(float(u) for u in batch["model_last_iter"])
        done += 1
    return done


def test_malformed_replay_addr_fails_fast_at_config_time():
    """Regression: a bad actor.replay.addr used to raise from int(port) at
    the FIRST PUSH, outside the drop-and-count try, killing the job loop
    mid-episode. It must fail at construction with a clear config error."""
    for addr in ("localhost", "host:", "host:not-a-port"):
        with pytest.raises(ValueError, match="host:port"):
            _make_actor(addr)


def test_push_with_unreachable_store_is_dropped_and_counted():
    """The documented drop semantics: a store outage past the retry budget
    loses the trajectory (counted), never the episode."""
    from distar_tpu.obs import get_registry

    actor = _make_actor("127.0.0.1:1")  # nothing listens on port 1
    actor._get_replay_client()._policy = NO_RETRY
    drops = get_registry().counter(
        "distar_actor_replay_push_failures_total",
        "replay-store inserts dropped after retries", player=PLAYER)
    before = drops.value
    actor.push_trajectory(PLAYER, _traj(0))  # must not raise
    assert drops.value == before + 1


def test_toy_fleet_enforces_samples_per_insert(tmp_path):
    """Train-through-the-store with the limiter on: the measured reuse ratio
    lands within +/-10% of the configured samples-per-insert."""
    store = ReplayStore(table_factory=lambda n: _table_cfg())
    server = ReplayServer(store, port=0).start()
    actor = _make_actor(f"{server.host}:{server.port}")
    producers = [_Producer(actor, start_uid=i * 100000) for i in range(2)]
    sampled = set()
    try:
        for p in producers:
            p.start()
        loader = ReplayDataLoader(
            SampleClient(server.host, server.port), PLAYER, batch_size=BATCH)
        target = 30  # learner step target: 30 batches -> 60 samples
        assert _drain(loader, target, sampled) == target
        for p in producers:
            p.stop()
        for p in producers:
            p.join(5.0)
        state = store.table(PLAYER).limiter.state()
        ratio = state["samples"] / max(state["inserts"] - MIN_SIZE, 1)
        assert abs(ratio - SPI) <= 0.1 * SPI, state
        loader._client.close()
    finally:
        for p in producers:
            p.stop()
        server.stop()


def test_store_kill_and_restart_recovers_every_acked_insert(tmp_path):
    """The chaos half: kill the store mid-run, restart it over the same
    spill, and (a) every acked-but-unsampled trajectory is back, (b) the
    learner reaches its target step count with zero manual intervention —
    the clients reconnect through their retry policies on their own."""
    spill_dir = str(tmp_path / "spill")

    def build():
        store = ReplayStore(table_factory=lambda n: _table_cfg(),
                            spill=SpillRing(spill_dir, max_items=1024))
        recovered = store.recover()
        return store, recovered

    store, recovered0 = build()
    assert recovered0 == 0
    server = ReplayServer(store, port=0).start()
    host, port = server.host, server.port
    actor = _make_actor(f"{host}:{port}")
    producer = _Producer(actor, start_uid=0)
    sampled = set()
    loader = ReplayDataLoader(SampleClient(host, port), PLAYER, batch_size=BATCH)
    chaos = ChaosInjector(seed=0)
    try:
        producer.start()
        _drain(loader, 8, sampled)  # phase 1: train a while

        # freeze producers so the acked-vs-sampled ledger is exact, then
        # kill the store with inserts acked and unsampled
        producer.stop()
        producer.join(5.0)
        acked = set(producer.acked)
        assert acked, "producer never acked anything"
        unsampled = acked - sampled
        assert unsampled, "kill point is vacuous: everything was already sampled"
        chaos.kill_role(server, name="replay")

        # restart on the same port over the same spill (the supervisor's job
        # in production; --type replay runs recovery before serving)
        store2, recovered = build()
        server2 = ReplayServer(store2, host=host, port=port).start()
        try:
            # (a) every acked-but-unsampled insert is resident again
            resident = {
                float(item.data[0]["model_last_iter"])
                for item in store2.table(PLAYER)._items.values()
            }
            assert unsampled <= resident
            assert recovered == len(resident)

            # (b) the SAME loader/producer objects keep working unassisted:
            # their clients redial through the retry policy
            producer2 = _Producer(actor, start_uid=500000)
            producer2.start()
            _drain(loader, 8, sampled)  # learner hits its remaining target
            producer2.stop()
            producer2.join(5.0)
        finally:
            server2.stop()
        loader._client.close()
    finally:
        producer.stop()
        server.stop()


def test_counter_demo_without_spill_loses_acked_data():
    """The demonstration the durability contract is measured against: same
    kill, no spill, no retry — acked-but-unsampled trajectories are gone."""
    store = ReplayStore(table_factory=lambda n: _table_cfg(spi=None))
    server = ReplayServer(store, port=0).start()
    host, port = server.host, server.port
    ic = InsertClient(host, port, retry_policy=NO_RETRY)
    acked = {float(i) for i in range(10) if ic.insert(PLAYER, _traj(i)) >= 0}
    assert len(acked) == 10
    sc = SampleClient(host, port, retry_policy=NO_RETRY)
    items, _info = sc.sample(PLAYER, batch_size=2, timeout_s=5.0)
    sampled = {float(t[0]["model_last_iter"]) for t in items}
    ChaosInjector(seed=0).kill_role(server, name="replay")

    # restart: nothing to recover from, and the NO_RETRY insert path fails
    store2 = ReplayStore(table_factory=lambda n: _table_cfg(spi=None))
    assert store2.recover() == 0
    server2 = ReplayServer(store2, host=host, port=port).start()
    try:
        assert store2.table(PLAYER).size() == 0  # acked data is gone
        lost = acked - sampled
        assert len(lost) >= 8, "the kill should have destroyed unsampled items"
        with pytest.raises(Exception):
            sc.sample(PLAYER, batch_size=1, timeout_s=0.2)  # nothing to serve
    finally:
        ic.close()
        sc.close()
        server2.stop()
